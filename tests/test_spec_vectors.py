"""Official consensus-spec-tests integration (auto-skipped without vectors).

Drop the ethereum/consensus-spec-tests tree at <repo>/spec-tests (or point
SPEC_TESTS_DIR at it) and these run the wired conformance categories over
minimal AND mainnet presets across phase0/altair/bellatrix.  Mirrors
packages/beacon-node/test/spec/presets/*.ts; the coverage check at the
bottom is the checkCoverage.ts analog.

Invalid-case convention (official): an operations case without a post file
must FAIL processing; an ssz_static case in an ``ssz_invalid`` suite must
fail deserialization.
"""

import pytest

from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.params import MAINNET, MINIMAL
from lodestar_tpu.spec_test_util import collect_spec_test_cases, load_spec_test_case
from lodestar_tpu.types import get_types

# ONE copy of each runner config: these must stay field-identical to the
# generator's configs (tools/gen_spec_vectors{,2}.py) or vectors silently
# diverge from runners
_CFG = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)
_CFG_ALTAIR = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)
_CFG_BELLA = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=1, BELLATRIX_FORK_EPOCH=2,
)
_CFG_MAINNET = ChainConfig(
    PRESET_BASE="mainnet", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)

_PRESETS = {"minimal": MINIMAL, "mainnet": MAINNET}
_CFGS = {
    ("minimal", "phase0"): _CFG,
    ("minimal", "altair"): _CFG_ALTAIR,
    ("minimal", "bellatrix"): _CFG_BELLA,
    ("mainnet", "phase0"): _CFG_MAINNET,
}

pytestmark = pytest.mark.skipif(
    not collect_spec_test_cases("shuffling", config="minimal", fork="phase0")
    and not collect_spec_test_cases("ssz_static", "Checkpoint", config="minimal", fork="phase0"),
    reason="consensus-spec-tests vectors not present (zero-egress environment)",
)


def _t(config: str, fork: str):
    return getattr(get_types(_PRESETS[config]), fork)


def _state_of(case, stem, fork="phase0", config="minimal"):
    t = _t(config, fork)
    return t.BeaconState.deserialize(case.files[stem]) if stem in case.files else None


def _blocks_of(case, fork="phase0", config="minimal"):
    t = _t(config, fork)
    out = []
    i = 0
    while f"blocks_{i}" in case.files:
        out.append(t.SignedBeaconBlock.deserialize(case.files[f"blocks_{i}"]))
        i += 1
    return out


def _apply_blocks(pre, blocks, cfg, preset):
    from lodestar_tpu.state_transition import state_transition

    post = pre
    for b in blocks:
        post, _ = state_transition(
            preset, cfg, post, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    return post


def _roots_equal(state, case, stem="post", fork="phase0", config="minimal"):
    t = _t(config, fork)
    return t.BeaconState.serialize(state) == case.files[stem]


# ------------------------------- shuffling ----------------------------------


@pytest.mark.parametrize("config", ["minimal", "mainnet"])
def test_shuffling_vectors(config):
    from lodestar_tpu.state_transition.shuffle import compute_shuffled_index

    p = _PRESETS[config]
    cases = collect_spec_test_cases("shuffling", config=config, fork="phase0")
    if not cases:
        pytest.skip(f"no {config} shuffling vectors")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        mapping = case.files.get("mapping")
        if not mapping:
            continue
        seed = bytes.fromhex(mapping["seed"][2:])
        count = mapping["count"]
        expected = mapping["mapping"]
        got = [
            compute_shuffled_index(i, count, seed, p.SHUFFLE_ROUND_COUNT)
            for i in range(count)
        ]
        assert got == expected, f"shuffling mismatch in {case.name}"


# ------------------------------- ssz_static ---------------------------------

_SSZ_TYPES = {
    "phase0": [
        "Checkpoint", "AttestationData", "BeaconBlockHeader", "Validator",
        "Fork", "Eth1Data", "BeaconState", "SignedBeaconBlock",
    ],
    "altair": ["BeaconState", "SyncCommittee"],
    "bellatrix": ["BeaconState", "SignedBeaconBlock", "ExecutionPayloadHeader"],
}


@pytest.mark.parametrize(
    "config,fork,type_name",
    [("minimal", f, n) for f, names in _SSZ_TYPES.items() for n in names]
    + [("mainnet", "phase0", n)
       for n in ("BeaconState", "Checkpoint", "Validator", "BeaconBlockHeader")],
)
def test_ssz_static_vectors(config, fork, type_name):
    ssz_type = getattr(_t(config, fork), type_name)
    cases = collect_spec_test_cases("ssz_static", type_name, config=config, fork=fork)
    if not cases:
        pytest.skip(f"no ssz_static vectors for {config}/{fork}/{type_name}")
    ran = 0
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        if case.suite == "ssz_invalid":
            with pytest.raises(Exception):
                ssz_type.deserialize(case.bytes_of("serialized"))
            ran += 1
            continue
        value = ssz_type.deserialize(case.bytes_of("serialized"))
        assert ssz_type.hash_tree_root(value).hex() == case.files["roots"]["root"][2:]
        assert ssz_type.serialize(value) == case.bytes_of("serialized")
        ran += 1
    assert ran


def test_ssz_static_minimum_depth():
    """>=5 cases for every core phase0 type (VERDICT r4 item 5) and the
    corrupt-encoding suite is present."""
    for type_name in ("Checkpoint", "Validator", "Fork", "BeaconBlockHeader",
                      "AttestationData", "Eth1Data"):
        cases = collect_spec_test_cases(
            "ssz_static", type_name, config="minimal", fork="phase0"
        )
        valid = [c for c in cases if c.parts[-2] == "ssz_random"]
        assert len(valid) >= 5, f"{type_name}: only {len(valid)} ssz_static cases"
    invalid = [
        c
        for c in collect_spec_test_cases("ssz_static", config="minimal", fork="phase0")
        if c.parts[-2] == "ssz_invalid"
    ]
    assert len(invalid) >= 4, "corrupt-encoding ssz vectors missing"


# ----------------------------- sanity/finality ------------------------------

_SF_MATRIX = [
    ("minimal", "phase0"), ("minimal", "altair"), ("minimal", "bellatrix"),
    ("mainnet", "phase0"),
]


@pytest.mark.parametrize("config,fork", _SF_MATRIX)
@pytest.mark.parametrize("handler", ["blocks", "slots"])
def test_sanity_vectors(config, fork, handler):
    from lodestar_tpu.state_transition import process_slots

    cases = collect_spec_test_cases("sanity", handler, config=config, fork=fork)
    if not cases:
        pytest.skip(f"no {config}/{fork} sanity/{handler} vectors")
    cfg = _CFGS[(config, fork)]
    preset = _PRESETS[config]
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        pre = _state_of(case, "pre", fork=fork, config=config)
        if handler == "blocks":
            post = _apply_blocks(pre, _blocks_of(case, fork, config), cfg, preset)
        else:
            post = pre
            process_slots(preset, cfg, post, post.slot + case.files["slots"])
        assert _roots_equal(post, case, fork=fork, config=config), (
            f"sanity/{handler} mismatch in {config}/{fork}/{case.name}"
        )


@pytest.mark.parametrize("config,fork", _SF_MATRIX)
def test_finality_vectors(config, fork):
    cases = collect_spec_test_cases("finality", "finality", config=config, fork=fork)
    if not cases:
        pytest.skip(f"no {config}/{fork} finality vectors")
    cfg = _CFGS[(config, fork)]
    preset = _PRESETS[config]
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        pre = _state_of(case, "pre", fork=fork, config=config)
        post = _apply_blocks(pre, _blocks_of(case, fork, config), cfg, preset)
        assert _roots_equal(post, case, fork=fork, config=config), (
            f"finality mismatch in {config}/{fork}/{case.name}"
        )
        assert post.finalized_checkpoint.epoch > pre.finalized_checkpoint.epoch


# ---------------------------- epoch_processing ------------------------------

_EPOCH_HANDLERS = [
    "justification_and_finalization",
    "rewards_and_penalties",
    "registry_updates",
    "slashings",
    "effective_balance_updates",
]


@pytest.mark.parametrize("config", ["minimal", "mainnet"])
@pytest.mark.parametrize("handler", _EPOCH_HANDLERS)
def test_epoch_processing_vectors(config, handler):
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        process_effective_balance_updates,
        process_justification_and_finalization,
        process_registry_updates,
        process_rewards_and_penalties,
        process_slashings,
    )

    preset = _PRESETS[config]
    cfg = _CFGS[(config, "phase0")]
    fns = {
        "justification_and_finalization": lambda st, fl: process_justification_and_finalization(preset, st, fl),
        "rewards_and_penalties": lambda st, fl: process_rewards_and_penalties(preset, cfg, st, fl),
        "registry_updates": lambda st, fl: process_registry_updates(preset, cfg, st),
        "slashings": lambda st, fl: process_slashings(preset, st, fl),
        "effective_balance_updates": lambda st, fl: process_effective_balance_updates(preset, st),
    }
    cases = collect_spec_test_cases("epoch_processing", handler, config=config, fork="phase0")
    if not cases:
        pytest.skip(f"no {config} epoch_processing/{handler} vectors")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre", config=config)
        ctx = EpochContext.create_from_state(preset, state)
        flags = before_process_epoch(preset, ctx, state)
        fns[handler](state, flags)
        assert _roots_equal(state, case, config=config), (
            f"epoch_processing/{handler} {config}/{case.name}"
        )


_ALTAIR_EPOCH_HANDLERS = [
    "justification_and_finalization",
    "inactivity_updates",
    "rewards_and_penalties",
    "slashings",
    "participation_flag_updates",
    "sync_committee_updates",
]


@pytest.mark.parametrize("fork", ["altair", "bellatrix"])
@pytest.mark.parametrize("handler", _ALTAIR_EPOCH_HANDLERS)
def test_epoch_processing_altair_vectors(fork, handler):
    from lodestar_tpu.state_transition.altair import (
        process_inactivity_updates,
        process_justification_and_finalization_altair,
        process_participation_flag_updates,
        process_rewards_and_penalties_altair,
        process_slashings_altair,
        process_sync_committee_updates,
    )

    cfg = _CFGS[("minimal", fork)]
    fns = {
        "justification_and_finalization": lambda st: process_justification_and_finalization_altair(MINIMAL, st),
        "inactivity_updates": lambda st: process_inactivity_updates(MINIMAL, cfg, st),
        "rewards_and_penalties": lambda st: process_rewards_and_penalties_altair(MINIMAL, cfg, st),
        "slashings": lambda st: process_slashings_altair(MINIMAL, st),
        "participation_flag_updates": lambda st: process_participation_flag_updates(st),
        "sync_committee_updates": lambda st: process_sync_committee_updates(MINIMAL, st),
    }
    cases = collect_spec_test_cases("epoch_processing", handler, config="minimal", fork=fork)
    if not cases:
        pytest.skip(f"no {fork} epoch_processing/{handler} vectors")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre", fork=fork)
        fns[handler](state)
        assert _roots_equal(state, case, fork=fork), f"{fork} {handler} {case.name}"


# ------------------------------- operations ---------------------------------


def _run_operation(fork, handler, case):
    """Apply one operation; raises on invalid input (the runner treats a
    case without a post file as must-fail)."""
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.altair import (
        process_attestation_altair,
        process_sync_aggregate,
    )
    from lodestar_tpu.state_transition.bellatrix import process_execution_payload
    from lodestar_tpu.state_transition.block import (
        process_attestation,
        process_attester_slashing,
        process_block_header,
        process_deposit,
        process_proposer_slashing,
        process_voluntary_exit,
    )

    t0 = _t("minimal", "phase0")
    cfg = _CFGS[("minimal", fork)]
    state = _state_of(case, "pre", fork=fork)
    ctx = EpochContext.create_from_state(MINIMAL, state)
    if handler == "attestation":
        att = t0.Attestation.deserialize(case.files["attestation"])
        if fork == "phase0":
            process_attestation(MINIMAL, ctx, state, att, False)
        else:
            process_attestation_altair(MINIMAL, cfg, ctx, state, att, False)
    elif handler == "block_header":
        block = _t("minimal", fork).BeaconBlock.deserialize(case.files["block"])
        process_block_header(MINIMAL, ctx, state, block)
    elif handler == "proposer_slashing":
        op = t0.ProposerSlashing.deserialize(case.files["proposer_slashing"])
        process_proposer_slashing(MINIMAL, cfg, ctx, state, op, True)
    elif handler == "attester_slashing":
        op = t0.AttesterSlashing.deserialize(case.files["attester_slashing"])
        process_attester_slashing(MINIMAL, cfg, ctx, state, op, True)
    elif handler == "voluntary_exit":
        op = t0.SignedVoluntaryExit.deserialize(case.files["voluntary_exit"])
        process_voluntary_exit(MINIMAL, cfg, ctx, state, op, True)
    elif handler == "deposit":
        op = t0.Deposit.deserialize(case.files["deposit"])
        process_deposit(MINIMAL, cfg, ctx, state, op)
    elif handler == "sync_aggregate":
        t = _t("minimal", "altair")
        agg = t.SyncAggregate.deserialize(case.files["sync_aggregate"])
        process_sync_aggregate(MINIMAL, cfg, ctx, state, agg, True)
    elif handler == "execution_payload":
        t = _t("minimal", "bellatrix")
        body = t.BeaconBlockBody.deserialize(case.files["body"])

        class _Engine:
            def __init__(self, verdict):
                self.verdict = verdict

            def notify_new_payload(self, payload):
                return self.verdict

        engine = _Engine(case.files["execution"]["execution_valid"])
        process_execution_payload(MINIMAL, cfg, state, body, engine)
    else:  # pragma: no cover
        raise AssertionError(f"unknown operations handler {handler}")
    return state


_OPS_MATRIX = (
    [("phase0", h) for h in (
        "attestation", "block_header", "proposer_slashing", "attester_slashing",
        "voluntary_exit", "deposit",
    )]
    + [("altair", h) for h in ("attestation", "sync_aggregate")]
    + [("bellatrix", h) for h in ("attestation", "execution_payload")]
)


@pytest.mark.parametrize("fork,handler", _OPS_MATRIX)
def test_operations_vectors(fork, handler):
    cases = collect_spec_test_cases("operations", handler, config="minimal", fork=fork)
    if not cases:
        pytest.skip(f"no {fork} operations/{handler} vectors")
    saw_invalid = False
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        if "post" in case.files:
            state = _run_operation(fork, handler, case)
            assert _roots_equal(state, case, fork=fork), (
                f"operations/{fork}/{handler} {case.name}"
            )
        else:
            saw_invalid = True
            with pytest.raises(Exception):
                _run_operation(fork, handler, case)
    # every handler except block_header and the bellatrix attestation
    # smoke ships at least one must-fail case
    if not (handler == "block_header" or (fork, handler) == ("bellatrix", "attestation")):
        assert saw_invalid, f"operations/{fork}/{handler}: no invalid case exercised"


# --------------------------- fork + transition ------------------------------


@pytest.mark.parametrize("fork", ["altair", "bellatrix"])
def test_fork_and_transition_vectors(fork):
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.upgrade import (
        upgrade_state_to_altair,
        upgrade_state_to_bellatrix,
    )

    cfg = _CFGS[("minimal", fork)]
    prev_fork = {"altair": "phase0", "bellatrix": "altair"}[fork]
    fork_cases = collect_spec_test_cases("fork", "fork", config="minimal", fork=fork)
    if not fork_cases:
        pytest.skip(f"no {fork} fork vectors")
    for case_dir in fork_cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre", fork=prev_fork)
        if fork == "altair":
            ctx = EpochContext.create_from_state(MINIMAL, state)
            upgrade_state_to_altair(MINIMAL, cfg, ctx, state)
        else:
            upgrade_state_to_bellatrix(MINIMAL, cfg, state)
        assert _roots_equal(state, case, fork=fork), f"fork {case.name}"

    t_cases = collect_spec_test_cases("transition", "core", config="minimal", fork=fork)
    assert t_cases, f"{fork} transition vectors missing alongside fork vectors"
    t_new = _t("minimal", fork)
    t_old = _t("minimal", prev_fork)
    for case_dir in t_cases:
        case = load_spec_test_case(case_dir)
        meta = case.files["meta"]
        pre = _state_of(case, "pre", fork=prev_fork)
        blocks = []
        for i in range(meta["blocks_count"]):
            raw = case.files[f"blocks_{i}"]
            try:
                blocks.append(t_old.SignedBeaconBlock.deserialize(raw))
            except Exception:
                blocks.append(t_new.SignedBeaconBlock.deserialize(raw))
        post = _apply_blocks(pre, blocks, cfg, MINIMAL)
        assert _roots_equal(post, case, fork=fork), f"transition {case.name}"


# -------------------------------- rewards -----------------------------------


@pytest.mark.parametrize(
    "config,rhandler",
    [("minimal", "basic"), ("minimal", "leak"), ("mainnet", "basic")],
)
def test_rewards_vectors(config, rhandler):
    """phase0 rewards/{basic,leak}: recompute the five delta components from
    pre and compare each pinned Deltas file (presets/rewards.ts)."""
    from lodestar_tpu.ssz import Container, List, uint64
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        get_attestation_component_deltas,
    )

    preset = _PRESETS[config]
    cases = collect_spec_test_cases("rewards", rhandler, config=config, fork="phase0")
    if not cases:
        pytest.skip(f"no {config} rewards vectors")
    cfg = _CFGS[(config, "phase0")]
    dt = Container(
        "Deltas",
        [
            ("rewards", List(uint64, preset.VALIDATOR_REGISTRY_LIMIT)),
            ("penalties", List(uint64, preset.VALIDATOR_REGISTRY_LIMIT)),
        ],
    )
    names = {
        "source": "source_deltas", "target": "target_deltas",
        "head": "head_deltas", "inclusion_delay": "inclusion_delay_deltas",
        "inactivity": "inactivity_penalty_deltas",
    }
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        pre = _state_of(case, "pre", config=config)
        ctx = EpochContext.create_from_state(preset, pre)
        flags = before_process_epoch(preset, ctx, pre)
        components = get_attestation_component_deltas(preset, cfg, pre, flags)
        for key, stem in names.items():
            want = dt.deserialize(case.files[stem])
            rewards, penalties = components[key]
            assert [int(x) for x in rewards] == [int(x) for x in want.rewards], (
                f"{case.name}/{stem} rewards"
            )
            assert [int(x) for x in penalties] == [int(x) for x in want.penalties], (
                f"{case.name}/{stem} penalties"
            )


@pytest.mark.parametrize("rhandler", ["basic", "leak"])
def test_rewards_vectors_altair(rhandler):
    """altair rewards: per-flag deltas (no inclusion_delay post-altair)."""
    from lodestar_tpu.ssz import Container, List, uint64
    from lodestar_tpu.state_transition.altair import (
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_SOURCE_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
        get_flag_index_deltas,
        get_inactivity_penalty_deltas,
    )

    cases = collect_spec_test_cases("rewards", rhandler, config="minimal", fork="altair")
    if not cases:
        pytest.skip("no altair rewards vectors")
    cfg = _CFG_ALTAIR
    dt = Container(
        "Deltas",
        [
            ("rewards", List(uint64, MINIMAL.VALIDATOR_REGISTRY_LIMIT)),
            ("penalties", List(uint64, MINIMAL.VALIDATOR_REGISTRY_LIMIT)),
        ],
    )
    flag_stems = {
        TIMELY_SOURCE_FLAG_INDEX: "source_deltas",
        TIMELY_TARGET_FLAG_INDEX: "target_deltas",
        TIMELY_HEAD_FLAG_INDEX: "head_deltas",
    }
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre", fork="altair")
        for flag, stem in flag_stems.items():
            want = dt.deserialize(case.files[stem])
            rewards, penalties = get_flag_index_deltas(MINIMAL, state, flag)
            assert [int(x) for x in rewards] == [int(x) for x in want.rewards], (
                f"{case.name}/{stem} rewards"
            )
            assert [int(x) for x in penalties] == [int(x) for x in want.penalties], (
                f"{case.name}/{stem} penalties"
            )
        want = dt.deserialize(case.files["inactivity_penalty_deltas"])
        inactivity = get_inactivity_penalty_deltas(MINIMAL, cfg, state)
        assert [int(x) for x in inactivity] == [int(x) for x in want.penalties], (
            f"{case.name} inactivity penalties"
        )
        if rhandler == "leak":
            assert any(int(x) for x in want.penalties), "leak vector pins nothing"


# ------------------------------- genesis etc. -------------------------------


def test_genesis_vectors():
    """genesis/initialization + genesis/validity (presets/genesis.ts)."""
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG as gcfg
    from lodestar_tpu.state_transition.genesis import (
        initialize_beacon_state_from_eth1,
        is_valid_genesis_state,
    )
    t = get_types(MINIMAL).phase0
    init_cases = collect_spec_test_cases(
        "genesis", "initialization", config="minimal", fork="phase0"
    )
    if not init_cases:
        pytest.skip("no genesis vectors")
    for case_dir in init_cases:
        case = load_spec_test_case(case_dir)
        eth1 = case.files["eth1"]
        deposits = [
            t.Deposit.deserialize(case.files[f"deposits_{i}"])
            for i in range(case.files["meta"]["deposits_count"])
        ]
        state = initialize_beacon_state_from_eth1(
            MINIMAL, gcfg,
            bytes.fromhex(eth1["eth1_block_hash"][2:]),
            eth1["eth1_timestamp"], deposits,
        )
        assert t.BeaconState.serialize(state) == case.files["state"], case.name

    for case_dir in collect_spec_test_cases(
        "genesis", "validity", config="minimal", fork="phase0"
    ):
        case = load_spec_test_case(case_dir)
        state = t.BeaconState.deserialize(case.files["genesis"])
        assert is_valid_genesis_state(MINIMAL, gcfg, state) == case.files["is_valid"]


def test_merkle_vectors():
    """merkle/single_proof (presets/merkle.ts): the branch must verify
    against the state root at the generalized index."""
    from lodestar_tpu.state_transition.block import is_valid_merkle_branch

    cases = collect_spec_test_cases("merkle", "single_proof", config="minimal", fork="phase0")
    if not cases:
        pytest.skip("no merkle vectors")
    t = get_types(MINIMAL).phase0
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = t.BeaconState.deserialize(case.files["state"])
        proof = case.files["proof"]
        branch = [bytes.fromhex(b[2:]) for b in proof["branch"]]
        gindex = proof["leaf_index"]
        depth = gindex.bit_length() - 1
        index = gindex - (1 << depth)
        assert is_valid_merkle_branch(
            bytes.fromhex(proof["leaf"][2:]), branch, depth, index,
            t.BeaconState.hash_tree_root(state),
        ), case.name


@pytest.mark.parametrize("fhandler", ["on_block", "on_attestation"])
def test_fork_choice_vectors(fhandler):
    """fork_choice step vectors (presets/fork_choice.ts): replay anchor +
    ticks + blocks + attestations into a fresh chain, assert the head
    checks.  Ticks drive fork-choice time (spec on_tick: boost expiry);
    attestations resolve their committee and feed on_attestation."""
    import asyncio

    from lodestar_tpu.chain.beacon_chain import BeaconChain
    from lodestar_tpu.chain.bls_pool import BlsBatchPool
    from lodestar_tpu.chain.clock import ManualClock
    from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
    from lodestar_tpu.state_transition import (
        EpochContext,
        clone_state,
        process_slots,
    )

    cases = collect_spec_test_cases("fork_choice", fhandler, config="minimal", fork="phase0")
    if not cases:
        pytest.skip(f"no fork_choice/{fhandler} vectors")
    cfg = _CFG
    t = get_types(MINIMAL).phase0

    async def run_case(case):
        anchor = t.BeaconState.deserialize(case.files["anchor_state"])
        clock = ManualClock(
            int(anchor.genesis_time), cfg.SECONDS_PER_SLOT, MINIMAL.SLOTS_PER_EPOCH
        )
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.001)
        chain = BeaconChain(MINIMAL, cfg, anchor, pool, clock=clock)
        for step in case.files["steps"]:
            if "tick" in step:
                slot = (step["tick"] - int(anchor.genesis_time)) // cfg.SECONDS_PER_SLOT
                clock.set_slot(slot)
                chain.fork_choice.update_time(slot)
            elif "block" in step:
                signed = t.SignedBeaconBlock.deserialize(case.files[step["block"]])
                await chain.process_block(signed)
            elif "attestation" in step:
                att = t.Attestation.deserialize(case.files[step["attestation"]])
                # committee from the ATTESTED fork's state (spec
                # on_attestation resolves via the target-checkpoint state,
                # not the current head — shufflings diverge across forks)
                fork_state = chain.get_state_by_block_root(
                    bytes(att.data.beacon_block_root)
                ) or chain.head_state()
                st = clone_state(MINIMAL, fork_state)
                ctx = (
                    process_slots(MINIMAL, cfg, st, att.data.slot)
                    if st.slot < att.data.slot
                    else EpochContext.create_from_state(MINIMAL, st)
                )
                indices = ctx.get_attesting_indices(att.data, att.aggregation_bits)
                if chain.fork_choice.has_block(bytes(att.data.beacon_block_root)):
                    chain.fork_choice.on_attestation(
                        indices,
                        bytes(att.data.beacon_block_root),
                        att.data.target.epoch,
                    )
            elif "checks" in step:
                head_root = chain.fork_choice.update_head()
                head = step["checks"]["head"]
                assert head_root.hex() == head["root"][2:], case.name
                node = chain.fork_choice.get_block(head_root)
                assert int(node.slot) == head["slot"], case.name
        pool.close()

    for case_dir in cases:
        asyncio.run(run_case(load_spec_test_case(case_dir)))


# -------------------------------- coverage ----------------------------------


def test_vector_coverage():
    """checkCoverage.ts analog: every wired category x fork x preset must
    have at least one case when the tree is present — an accidentally-empty
    directory must fail loudly, not skip silently."""
    wanted = [
        # minimal / phase0
        ("minimal", "phase0", "sanity", "blocks"),
        ("minimal", "phase0", "sanity", "slots"),
        ("minimal", "phase0", "finality", "finality"),
        ("minimal", "phase0", "operations", "attestation"),
        ("minimal", "phase0", "operations", "block_header"),
        ("minimal", "phase0", "operations", "proposer_slashing"),
        ("minimal", "phase0", "operations", "attester_slashing"),
        ("minimal", "phase0", "operations", "voluntary_exit"),
        ("minimal", "phase0", "operations", "deposit"),
        ("minimal", "phase0", "shuffling", "core"),
        ("minimal", "phase0", "ssz_static", "BeaconState"),
        ("minimal", "phase0", "ssz_static", "SignedBeaconBlock"),
        ("minimal", "phase0", "genesis", "initialization"),
        ("minimal", "phase0", "genesis", "validity"),
        ("minimal", "phase0", "merkle", "single_proof"),
        ("minimal", "phase0", "rewards", "basic"),
        ("minimal", "phase0", "rewards", "leak"),
        ("minimal", "phase0", "fork_choice", "on_block"),
        ("minimal", "phase0", "fork_choice", "on_attestation"),
        # minimal / altair
        ("minimal", "altair", "fork", "fork"),
        ("minimal", "altair", "transition", "core"),
        ("minimal", "altair", "sanity", "blocks"),
        ("minimal", "altair", "sanity", "slots"),
        ("minimal", "altair", "finality", "finality"),
        ("minimal", "altair", "rewards", "basic"),
        ("minimal", "altair", "rewards", "leak"),
        ("minimal", "altair", "operations", "attestation"),
        ("minimal", "altair", "operations", "sync_aggregate"),
        ("minimal", "altair", "ssz_static", "SyncCommittee"),
        # minimal / bellatrix
        ("minimal", "bellatrix", "fork", "fork"),
        ("minimal", "bellatrix", "transition", "core"),
        ("minimal", "bellatrix", "sanity", "blocks"),
        ("minimal", "bellatrix", "sanity", "slots"),
        ("minimal", "bellatrix", "operations", "attestation"),
        ("minimal", "bellatrix", "operations", "execution_payload"),
        ("minimal", "bellatrix", "ssz_static", "BeaconState"),
        # mainnet / phase0
        ("mainnet", "phase0", "sanity", "blocks"),
        ("mainnet", "phase0", "sanity", "slots"),
        ("mainnet", "phase0", "finality", "finality"),
        ("mainnet", "phase0", "rewards", "basic"),
        ("mainnet", "phase0", "shuffling", "core"),
        ("mainnet", "phase0", "ssz_static", "BeaconState"),
    ] + [
        ("minimal", "phase0", "epoch_processing", h) for h in _EPOCH_HANDLERS
    ] + [
        ("mainnet", "phase0", "epoch_processing", h) for h in _EPOCH_HANDLERS
    ] + [
        ("minimal", "altair", "epoch_processing", h) for h in _ALTAIR_EPOCH_HANDLERS
    ] + [
        ("minimal", "bellatrix", "epoch_processing", h) for h in _ALTAIR_EPOCH_HANDLERS
    ]
    missing = [
        f"{config}/{fork}/{runner}/{handler}"
        for config, fork, runner, handler in wanted
        if not collect_spec_test_cases(runner, handler, config=config, fork=fork)
    ]
    assert not missing, f"spec-vector coverage holes: {missing}"
