"""Official consensus-spec-tests integration (auto-skipped without vectors).

Drop the ethereum/consensus-spec-tests tree at <repo>/spec-tests (or point
SPEC_TESTS_DIR at it) and these run the conformance categories the harness
currently wires: shuffling, ssz_static (Checkpoint/AttestationData/
BeaconBlockHeader), operations/voluntary_exit-style smoke.  Mirrors
packages/beacon-node/test/spec/presets/*.ts.
"""

import pytest

from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.spec_test_util import collect_spec_test_cases, load_spec_test_case
from lodestar_tpu.types import get_types

# ONE copy of each runner config: these must stay field-identical to the
# generator's CFG / CFG_ALTAIR or vectors silently diverge from runners
_CFG = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)
_CFG_ALTAIR = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)

pytestmark = pytest.mark.skipif(
    not collect_spec_test_cases("shuffling", config="minimal", fork="phase0")
    and not collect_spec_test_cases("ssz_static", "Checkpoint", config="minimal", fork="phase0"),
    reason="consensus-spec-tests vectors not present (zero-egress environment)",
)


def test_shuffling_vectors():
    from lodestar_tpu.state_transition.shuffle import compute_shuffled_index

    cases = collect_spec_test_cases("shuffling", config="minimal", fork="phase0")
    assert cases
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        mapping = case.files.get("mapping")
        if not mapping:
            continue
        seed = bytes.fromhex(mapping["seed"][2:])
        count = mapping["count"]
        expected = mapping["mapping"]
        got = [
            compute_shuffled_index(i, count, seed, MINIMAL.SHUFFLE_ROUND_COUNT)
            for i in range(count)
        ]
        assert got == expected, f"shuffling mismatch in {case.name}"


@pytest.mark.parametrize("type_name", ["Checkpoint", "AttestationData", "BeaconBlockHeader", "Validator"])
def test_ssz_static_vectors(type_name):
    t = get_types(MINIMAL).phase0
    ssz_type = getattr(t, type_name)
    cases = collect_spec_test_cases("ssz_static", type_name, config="minimal", fork="phase0")
    if not cases:
        pytest.skip(f"no ssz_static vectors for {type_name}")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        value = ssz_type.deserialize(case.bytes_of("serialized"))
        assert ssz_type.hash_tree_root(value).hex() == case.files["roots"]["root"][2:]
        assert ssz_type.serialize(value) == case.bytes_of("serialized")


def _state_of(case, stem, fork="phase0"):
    t = getattr(get_types(MINIMAL), fork)
    return t.BeaconState.deserialize(case.files[stem]) if stem in case.files else None


def _blocks_of(case, fork="phase0"):
    t = getattr(get_types(MINIMAL), fork)
    out = []
    i = 0
    while f"blocks_{i}" in case.files:
        out.append(t.SignedBeaconBlock.deserialize(case.files[f"blocks_{i}"]))
        i += 1
    return out


def _apply_blocks(pre, blocks, cfg=None):
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.state_transition import state_transition

    cfg = cfg or _CFG
    post = pre
    for b in blocks:
        post, _ = state_transition(
            MINIMAL, cfg, post, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    return post


def _roots_equal(state, case, stem="post", fork="phase0"):
    t = getattr(get_types(MINIMAL), fork)
    return t.BeaconState.serialize(state) == case.files[stem]


@pytest.mark.parametrize("handler", ["blocks", "slots"])
def test_sanity_vectors(handler):
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.state_transition import process_slots

    cases = collect_spec_test_cases("sanity", handler, config="minimal", fork="phase0")
    if not cases:
        pytest.skip("no sanity vectors")
    cfg = _CFG
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        pre = _state_of(case, "pre")
        if handler == "blocks":
            post = _apply_blocks(pre, _blocks_of(case))
        else:
            post = pre
            process_slots(MINIMAL, cfg, post, post.slot + case.files["slots"])
        assert _roots_equal(post, case), f"sanity/{handler} mismatch in {case.name}"


def test_finality_vectors():
    cases = collect_spec_test_cases("finality", "finality", config="minimal", fork="phase0")
    if not cases:
        pytest.skip("no finality vectors")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        pre = _state_of(case, "pre")
        post = _apply_blocks(pre, _blocks_of(case))
        assert _roots_equal(post, case), f"finality mismatch in {case.name}"
        assert post.finalized_checkpoint.epoch > pre.finalized_checkpoint.epoch


_EPOCH_HANDLERS = [
    "justification_and_finalization",
    "rewards_and_penalties",
    "registry_updates",
    "slashings",
    "effective_balance_updates",
]


@pytest.mark.parametrize("handler", _EPOCH_HANDLERS)
def test_epoch_processing_vectors(handler):
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        process_effective_balance_updates,
        process_justification_and_finalization,
        process_registry_updates,
        process_rewards_and_penalties,
        process_slashings,
    )

    cfg = _CFG
    fns = {
        "justification_and_finalization": lambda st, fl: process_justification_and_finalization(MINIMAL, st, fl),
        "rewards_and_penalties": lambda st, fl: process_rewards_and_penalties(MINIMAL, cfg, st, fl),
        "registry_updates": lambda st, fl: process_registry_updates(MINIMAL, cfg, st),
        "slashings": lambda st, fl: process_slashings(MINIMAL, st, fl),
        "effective_balance_updates": lambda st, fl: process_effective_balance_updates(MINIMAL, st),
    }
    cases = collect_spec_test_cases("epoch_processing", handler, config="minimal", fork="phase0")
    if not cases:
        pytest.skip(f"no epoch_processing/{handler} vectors")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre")
        ctx = EpochContext.create_from_state(MINIMAL, state)
        flags = before_process_epoch(MINIMAL, ctx, state)
        fns[handler](state, flags)
        assert _roots_equal(state, case), f"epoch_processing/{handler} {case.name}"


@pytest.mark.parametrize("handler", ["attestation", "block_header"])
def test_operations_vectors(handler):
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.block import (
        process_attestation,
        process_block_header,
    )

    cases = collect_spec_test_cases("operations", handler, config="minimal", fork="phase0")
    if not cases:
        pytest.skip(f"no operations/{handler} vectors")
    t = get_types(MINIMAL).phase0
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre")
        ctx = EpochContext.create_from_state(MINIMAL, state)
        if handler == "attestation":
            att = t.Attestation.deserialize(case.files["attestation"])
            process_attestation(MINIMAL, ctx, state, att, False)
        else:
            block = t.BeaconBlock.deserialize(case.files["block"])
            process_block_header(MINIMAL, ctx, state, block)
        assert _roots_equal(state, case), f"operations/{handler} {case.name}"


def test_fork_and_transition_vectors():
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.upgrade import upgrade_state_to_altair

    cfg_altair = _CFG_ALTAIR
    fork_cases = collect_spec_test_cases("fork", "fork", config="minimal", fork="altair")
    if not fork_cases:
        pytest.skip("no fork vectors")
    for case_dir in fork_cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre", fork="phase0")
        ctx = EpochContext.create_from_state(MINIMAL, state)
        upgrade_state_to_altair(MINIMAL, cfg_altair, ctx, state)
        assert _roots_equal(state, case, fork="altair"), f"fork {case.name}"

    t_cases = collect_spec_test_cases("transition", "core", config="minimal", fork="altair")
    assert t_cases, "transition vectors missing alongside fork vectors"
    alt = get_types(MINIMAL).altair
    ph0 = get_types(MINIMAL).phase0
    for case_dir in t_cases:
        case = load_spec_test_case(case_dir)
        meta = case.files["meta"]
        pre = _state_of(case, "pre", fork="phase0")
        blocks = []
        for i in range(meta["blocks_count"]):
            raw = case.files[f"blocks_{i}"]
            try:
                blocks.append(ph0.SignedBeaconBlock.deserialize(raw))
            except Exception:
                blocks.append(alt.SignedBeaconBlock.deserialize(raw))
        post = _apply_blocks(pre, blocks, cfg_altair)
        assert _roots_equal(post, case, fork="altair"), f"transition {case.name}"


_ALTAIR_EPOCH_HANDLERS = [
    "justification_and_finalization",
    "inactivity_updates",
    "rewards_and_penalties",
    "slashings",
    "participation_flag_updates",
    "sync_committee_updates",
]


@pytest.mark.parametrize("handler", _ALTAIR_EPOCH_HANDLERS)
def test_epoch_processing_altair_vectors(handler):
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.state_transition.altair import (
        process_inactivity_updates,
        process_justification_and_finalization_altair,
        process_participation_flag_updates,
        process_rewards_and_penalties_altair,
        process_slashings_altair,
        process_sync_committee_updates,
    )

    cfg = _CFG_ALTAIR
    fns = {
        "justification_and_finalization": lambda st: process_justification_and_finalization_altair(MINIMAL, st),
        "inactivity_updates": lambda st: process_inactivity_updates(MINIMAL, cfg, st),
        "rewards_and_penalties": lambda st: process_rewards_and_penalties_altair(MINIMAL, cfg, st),
        "slashings": lambda st: process_slashings_altair(MINIMAL, st),
        "participation_flag_updates": lambda st: process_participation_flag_updates(st),
        "sync_committee_updates": lambda st: process_sync_committee_updates(MINIMAL, st),
    }
    cases = collect_spec_test_cases("epoch_processing", handler, config="minimal", fork="altair")
    if not cases:
        pytest.skip(f"no altair epoch_processing/{handler} vectors")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = _state_of(case, "pre", fork="altair")
        fns[handler](state)
        assert _roots_equal(state, case, fork="altair"), f"altair {handler} {case.name}"


@pytest.mark.parametrize("rhandler", ["basic", "leak"])
def test_rewards_vectors(rhandler):
    """rewards/{basic,leak}: recompute the five delta components from pre
    and compare each pinned Deltas file (presets/rewards.ts)."""
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.ssz import Container, List, uint64
    from lodestar_tpu.state_transition import EpochContext
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        get_attestation_component_deltas,
    )

    cases = collect_spec_test_cases("rewards", rhandler, config="minimal", fork="phase0")
    if not cases:
        pytest.skip("no rewards vectors")
    cfg = _CFG
    dt = Container(
        "Deltas",
        [
            ("rewards", List(uint64, MINIMAL.VALIDATOR_REGISTRY_LIMIT)),
            ("penalties", List(uint64, MINIMAL.VALIDATOR_REGISTRY_LIMIT)),
        ],
    )
    names = {
        "source": "source_deltas", "target": "target_deltas",
        "head": "head_deltas", "inclusion_delay": "inclusion_delay_deltas",
        "inactivity": "inactivity_penalty_deltas",
    }
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        pre = _state_of(case, "pre")
        ctx = EpochContext.create_from_state(MINIMAL, pre)
        flags = before_process_epoch(MINIMAL, ctx, pre)
        components = get_attestation_component_deltas(MINIMAL, cfg, pre, flags)
        for key, stem in names.items():
            want = dt.deserialize(case.files[stem])
            rewards, penalties = components[key]
            assert [int(x) for x in rewards] == [int(x) for x in want.rewards], (
                f"{case.name}/{stem} rewards"
            )
            assert [int(x) for x in penalties] == [int(x) for x in want.penalties], (
                f"{case.name}/{stem} penalties"
            )


def test_genesis_vectors():
    """genesis/initialization + genesis/validity (presets/genesis.ts)."""
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG as gcfg
    from lodestar_tpu.state_transition.genesis import (
        initialize_beacon_state_from_eth1,
        is_valid_genesis_state,
    )
    t = get_types(MINIMAL).phase0
    init_cases = collect_spec_test_cases(
        "genesis", "initialization", config="minimal", fork="phase0"
    )
    if not init_cases:
        pytest.skip("no genesis vectors")
    for case_dir in init_cases:
        case = load_spec_test_case(case_dir)
        eth1 = case.files["eth1"]
        deposits = [
            t.Deposit.deserialize(case.files[f"deposits_{i}"])
            for i in range(case.files["meta"]["deposits_count"])
        ]
        state = initialize_beacon_state_from_eth1(
            MINIMAL, gcfg,
            bytes.fromhex(eth1["eth1_block_hash"][2:]),
            eth1["eth1_timestamp"], deposits,
        )
        assert t.BeaconState.serialize(state) == case.files["state"], case.name

    for case_dir in collect_spec_test_cases(
        "genesis", "validity", config="minimal", fork="phase0"
    ):
        case = load_spec_test_case(case_dir)
        state = t.BeaconState.deserialize(case.files["genesis"])
        assert is_valid_genesis_state(MINIMAL, gcfg, state) == case.files["is_valid"]


def test_merkle_vectors():
    """merkle/single_proof (presets/merkle.ts): the branch must verify
    against the state root at the generalized index."""
    from lodestar_tpu.state_transition.block import is_valid_merkle_branch

    cases = collect_spec_test_cases("merkle", "single_proof", config="minimal", fork="phase0")
    if not cases:
        pytest.skip("no merkle vectors")
    t = get_types(MINIMAL).phase0
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        state = t.BeaconState.deserialize(case.files["state"])
        proof = case.files["proof"]
        branch = [bytes.fromhex(b[2:]) for b in proof["branch"]]
        gindex = proof["leaf_index"]
        depth = gindex.bit_length() - 1
        index = gindex - (1 << depth)
        assert is_valid_merkle_branch(
            bytes.fromhex(proof["leaf"][2:]), branch, depth, index,
            t.BeaconState.hash_tree_root(state),
        ), case.name


@pytest.mark.parametrize("fhandler", ["on_block", "on_attestation"])
def test_fork_choice_vectors(fhandler):
    """fork_choice step vectors (presets/fork_choice.ts): replay anchor +
    ticks + blocks + attestations into a fresh chain, assert the head
    checks.  Ticks drive fork-choice time (spec on_tick: boost expiry);
    attestations resolve their committee and feed on_attestation."""
    import asyncio

    from lodestar_tpu.chain.beacon_chain import BeaconChain
    from lodestar_tpu.chain.bls_pool import BlsBatchPool
    from lodestar_tpu.chain.clock import ManualClock
    from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier
    from lodestar_tpu.state_transition import (
        EpochContext,
        clone_state,
        process_slots,
    )

    cases = collect_spec_test_cases("fork_choice", fhandler, config="minimal", fork="phase0")
    if not cases:
        pytest.skip(f"no fork_choice/{fhandler} vectors")
    cfg = _CFG
    t = get_types(MINIMAL).phase0

    async def run_case(case):
        anchor = t.BeaconState.deserialize(case.files["anchor_state"])
        clock = ManualClock(
            int(anchor.genesis_time), cfg.SECONDS_PER_SLOT, MINIMAL.SLOTS_PER_EPOCH
        )
        pool = BlsBatchPool(PyBlsVerifier(), max_buffer_wait=0.001)
        chain = BeaconChain(MINIMAL, cfg, anchor, pool, clock=clock)
        for step in case.files["steps"]:
            if "tick" in step:
                slot = (step["tick"] - int(anchor.genesis_time)) // cfg.SECONDS_PER_SLOT
                clock.set_slot(slot)
                chain.fork_choice.update_time(slot)
            elif "block" in step:
                signed = t.SignedBeaconBlock.deserialize(case.files[step["block"]])
                await chain.process_block(signed)
            elif "attestation" in step:
                att = t.Attestation.deserialize(case.files[step["attestation"]])
                # committee from the ATTESTED fork's state (spec
                # on_attestation resolves via the target-checkpoint state,
                # not the current head — shufflings diverge across forks)
                fork_state = chain.get_state_by_block_root(
                    bytes(att.data.beacon_block_root)
                ) or chain.head_state()
                st = clone_state(MINIMAL, fork_state)
                ctx = (
                    process_slots(MINIMAL, cfg, st, att.data.slot)
                    if st.slot < att.data.slot
                    else EpochContext.create_from_state(MINIMAL, st)
                )
                indices = ctx.get_attesting_indices(att.data, att.aggregation_bits)
                if chain.fork_choice.has_block(bytes(att.data.beacon_block_root)):
                    chain.fork_choice.on_attestation(
                        indices,
                        bytes(att.data.beacon_block_root),
                        att.data.target.epoch,
                    )
            elif "checks" in step:
                head_root = chain.fork_choice.update_head()
                head = step["checks"]["head"]
                assert head_root.hex() == head["root"][2:], case.name
                node = chain.fork_choice.get_block(head_root)
                assert int(node.slot) == head["slot"], case.name
        pool.close()

    for case_dir in cases:
        asyncio.run(run_case(load_spec_test_case(case_dir)))


def test_vector_coverage():
    """checkCoverage.ts analog: every wired category must have at least
    one case when the tree is present — an accidentally-empty directory
    must fail loudly, not skip silently."""
    wanted = [
        ("sanity", "blocks", "phase0"),
        ("sanity", "slots", "phase0"),
        ("finality", "finality", "phase0"),
        ("operations", "attestation", "phase0"),
        ("operations", "block_header", "phase0"),
        ("shuffling", "core", "phase0"),
        ("ssz_static", "BeaconState", "phase0"),
        ("genesis", "initialization", "phase0"),
        ("genesis", "validity", "phase0"),
        ("merkle", "single_proof", "phase0"),
        ("rewards", "basic", "phase0"),
        ("rewards", "leak", "phase0"),
        ("fork_choice", "on_block", "phase0"),
        ("fork_choice", "on_attestation", "phase0"),
        ("fork", "fork", "altair"),
        ("transition", "core", "altair"),
    ] + [("epoch_processing", h, "phase0") for h in _EPOCH_HANDLERS] + [
        ("epoch_processing", h, "altair") for h in _ALTAIR_EPOCH_HANDLERS
    ]
    missing = [
        f"{runner}/{handler}"
        for runner, handler, fork in wanted
        if not collect_spec_test_cases(runner, handler, config="minimal", fork=fork)
    ]
    assert not missing, f"spec-vector coverage holes: {missing}"
