"""Official consensus-spec-tests integration (auto-skipped without vectors).

Drop the ethereum/consensus-spec-tests tree at <repo>/spec-tests (or point
SPEC_TESTS_DIR at it) and these run the conformance categories the harness
currently wires: shuffling, ssz_static (Checkpoint/AttestationData/
BeaconBlockHeader), operations/voluntary_exit-style smoke.  Mirrors
packages/beacon-node/test/spec/presets/*.ts.
"""

import pytest

from lodestar_tpu.params import MINIMAL
from lodestar_tpu.spec_test_util import collect_spec_test_cases, load_spec_test_case
from lodestar_tpu.types import get_types

pytestmark = pytest.mark.skipif(
    not collect_spec_test_cases("shuffling", config="minimal", fork="phase0")
    and not collect_spec_test_cases("ssz_static", "Checkpoint", config="minimal", fork="phase0"),
    reason="consensus-spec-tests vectors not present (zero-egress environment)",
)


def test_shuffling_vectors():
    from lodestar_tpu.state_transition.shuffle import compute_shuffled_index

    cases = collect_spec_test_cases("shuffling", config="minimal", fork="phase0")
    assert cases
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        mapping = case.files.get("mapping")
        if not mapping:
            continue
        seed = bytes.fromhex(mapping["seed"][2:])
        count = mapping["count"]
        expected = mapping["mapping"]
        got = [
            compute_shuffled_index(i, count, seed, MINIMAL.SHUFFLE_ROUND_COUNT)
            for i in range(count)
        ]
        assert got == expected, f"shuffling mismatch in {case.name}"


@pytest.mark.parametrize("type_name", ["Checkpoint", "AttestationData", "BeaconBlockHeader", "Validator"])
def test_ssz_static_vectors(type_name):
    t = get_types(MINIMAL).phase0
    ssz_type = getattr(t, type_name)
    cases = collect_spec_test_cases("ssz_static", type_name, config="minimal", fork="phase0")
    if not cases:
        pytest.skip(f"no ssz_static vectors for {type_name}")
    for case_dir in cases:
        case = load_spec_test_case(case_dir)
        value = ssz_type.deserialize(case.bytes_of("serialized"))
        assert ssz_type.hash_tree_root(value).hex() == case.files["roots"]["root"][2:]
        assert ssz_type.serialize(value) == case.bytes_of("serialized")
