"""Differential tests: ops.limbs (JAX 16-bit-limb Fq) vs the bigint oracle.

Strategy mirrors how the reference differential-tests its BLS backends
against each other (packages/beacon-node/test/spec/general/bls.ts runs the
same vectors through the facade): every kernel result is compared to
``lodestar_tpu.crypto.bls.fields`` on batches of random and adversarial
inputs.
"""

import secrets

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto.bls.fields import P
from lodestar_tpu.ops import limbs as fl


def rand_ints(n, bound=P):
    return [secrets.randbelow(bound) for _ in range(n)]


def adversarial_ints():
    """Edge values for carry/fold paths."""
    vals = [0, 1, 2, P - 1, P - 2, P, P + 1, (1 << 381) - 1, (1 << 384) - 1]
    # all-0xffff digit patterns and single-high-digit patterns
    vals.append((1 << fl.VALUE_BITS) - 1)
    vals.append(((1 << fl.VALUE_BITS) - 1) - 0xFFFF)
    for k in (0, 12, 24, 25):
        vals.append(0xFFFF << (16 * k))
    return [v % (1 << fl.VALUE_BITS) for v in vals]


def to_dev(ints):
    return jnp.asarray(fl.ints_to_limbs(ints))


def check_batch(arr, expected_ints):
    arr = np.asarray(arr)
    assert arr.shape[-1] == fl.NLIMBS
    for row, exp in zip(arr.reshape(-1, fl.NLIMBS), expected_ints):
        got = fl.limbs_to_int(row)
        # semi-strict representation: digits <= 2^8 (fixed point of the
        # branch-free folding carries), value < ~1.004 * 2^VALUE_BITS
        assert got < (1 << (fl.VALUE_BITS + 1)), "strict invariant violated (value)"
        assert np.all(row <= (1 << fl.LIMB_BITS)), "strict invariant violated (loose digit)"
        assert got % P == exp % P, f"mod-p mismatch: got {hex(got)} want {hex(exp % P)}"


class TestPacking:
    def test_roundtrip(self):
        for v in rand_ints(20, 1 << fl.VALUE_BITS) + adversarial_ints():
            assert fl.limbs_to_int(fl.int_to_limbs(v)) == v

    def test_out_of_range(self):
        with pytest.raises(ValueError):
            fl.int_to_limbs(1 << fl.VALUE_BITS)
        with pytest.raises(ValueError):
            fl.int_to_limbs(-1)

    def test_batch_matches_scalar(self):
        # the vectorized byte->limb path (TpuBlsVerifier packing hot path)
        # is bit-identical to the per-digit scalar reference
        vals = rand_ints(20, 1 << fl.VALUE_BITS) + adversarial_ints()
        got = fl.ints_to_limbs(vals)
        want = np.stack([fl.int_to_limbs(v) for v in vals])
        assert got.dtype == want.dtype and (got == want).all()
        assert fl.ints_to_limbs([]).shape == (0, fl.NLIMBS)

    def test_batch_out_of_range(self):
        with pytest.raises(ValueError):
            fl.ints_to_limbs([1, 1 << fl.VALUE_BITS])
        with pytest.raises(ValueError):
            fl.ints_to_limbs([-1])


class TestRing:
    def test_add_strict_chain(self):
        # chains of lazy adds then one fp_strict
        a, b, c, d = (rand_ints(64, 1 << fl.VALUE_BITS) for _ in range(4))
        out = fl.fp_strict(fl.fp_add(fl.fp_add(to_dev(a), to_dev(b)), fl.fp_add(to_dev(c), to_dev(d))))
        check_batch(out, [w + x + y + z for w, x, y, z in zip(a, b, c, d)])

    def test_sub(self):
        a, b = rand_ints(64, 1 << fl.VALUE_BITS), rand_ints(64, 1 << fl.VALUE_BITS)
        out = fl.fp_sub(to_dev(a), to_dev(b))
        check_batch(out, [(x - y) % P for x, y in zip(a, b)])

    def test_sub_loose_inputs(self):
        # minuend loose from a 4-add chain; subtrahend loose from one add
        a, b, c, d = (rand_ints(32, 1 << fl.VALUE_BITS) for _ in range(4))
        minuend = fl.fp_add(fl.fp_add(to_dev(a), to_dev(b)), to_dev(c))  # digits < 3*2^16 < 2^18
        subtrahend = fl.fp_add(to_dev(d), to_dev(a))  # digits < 2^17 < 2^20 bound
        out = fl.fp_sub(minuend, subtrahend)
        check_batch(out, [(x + y + z - (w + x)) % P for x, y, z, w in zip(a, b, c, d)])

    def test_neg(self):
        a = rand_ints(32, 1 << fl.VALUE_BITS) + adversarial_ints()
        out = fl.fp_neg(to_dev(a))
        check_batch(out, [(-x) % P for x in a])

    def test_mul_random(self):
        a, b = rand_ints(128, 1 << fl.VALUE_BITS), rand_ints(128, 1 << fl.VALUE_BITS)
        out = fl.fp_mul(to_dev(a), to_dev(b))
        check_batch(out, [x * y % P for x, y in zip(a, b)])

    def test_mul_adversarial(self):
        adv = adversarial_ints()
        a = adv * len(adv)
        b = [v for v in adv for _ in adv]
        out = fl.fp_mul(to_dev(a), to_dev(b))
        check_batch(out, [x * y % P for x, y in zip(a, b)])

    def test_mul_loose_flag(self):
        a, b, c = rand_ints(16, 1 << fl.VALUE_BITS), rand_ints(16, 1 << fl.VALUE_BITS), rand_ints(16, 1 << fl.VALUE_BITS)
        loose = fl.fp_add(to_dev(a), to_dev(b))
        out = fl.fp_mul(loose, to_dev(c), a_strict=False)
        check_batch(out, [(x + y) * z % P for x, y, z in zip(a, b, c)])

    def test_mul_small(self):
        a = rand_ints(32, 1 << fl.VALUE_BITS) + adversarial_ints()
        for k in (0, 1, 2, 3, 8, 12, (1 << 14) - 1):
            out = fl.fp_mul_small(to_dev(a), k)
            check_batch(out, [x * k % P for x in a])

    def test_batch_shapes(self):
        # leading axes broadcast: (2, 3) batch
        a = rand_ints(6)
        b = rand_ints(6)
        av = to_dev(a).reshape(2, 3, fl.NLIMBS)
        bv = to_dev(b).reshape(2, 3, fl.NLIMBS)
        out = np.asarray(fl.fp_mul(av, bv)).reshape(6, fl.NLIMBS)
        check_batch(out, [x * y % P for x, y in zip(a, b)])


class TestReduceCompare:
    def test_reduce_full(self):
        vals = rand_ints(64, 1 << fl.VALUE_BITS) + adversarial_ints()
        out = np.asarray(fl.fp_reduce_full(to_dev(vals)))
        for row, v in zip(out, vals):
            got = fl.limbs_to_int(row)
            assert got == v % P

    def test_eq(self):
        a = rand_ints(16)
        shifted = [(x + P) for x in a]  # same residue, different representation
        assert bool(jnp.all(fl.fp_eq(to_dev(a), to_dev(shifted))))
        b = [(x + 1) % P for x in a]
        assert not bool(jnp.any(fl.fp_eq(to_dev(a), to_dev(b))))

    def test_is_zero(self):
        vals = [0, P, 2 * P, 1, P - 1, 7 * P]
        out = np.asarray(fl.fp_is_zero(to_dev(vals)))
        assert list(out) == [True, True, True, False, False, True]


class TestPowInv:
    def test_pow_static(self):
        a = rand_ints(8)
        for e in (0, 1, 2, 3, 65537, P - 2):
            out = np.asarray(fl.fp_pow_static(to_dev(a), e))
            for row, x in zip(out, a):
                assert fl.limbs_to_int(row) % P == pow(x, e, P)

    def test_inv(self):
        a = [x for x in rand_ints(8) if x]
        out = np.asarray(fl.fp_inv(to_dev(a)))
        for row, x in zip(out, a):
            assert fl.limbs_to_int(row) % P == pow(x, P - 2, P)

    def test_inv_jit(self):
        a = [x for x in rand_ints(4) if x]
        f = jax.jit(fl.fp_inv)
        out = np.asarray(f(to_dev(a)))
        for row, x in zip(out, a):
            assert (fl.limbs_to_int(row) * x) % P == 1


class TestJit:
    def test_mul_under_jit_and_vmap(self):
        a, b = rand_ints(32), rand_ints(32)
        f = jax.jit(fl.fp_mul)
        check_batch(f(to_dev(a), to_dev(b)), [x * y % P for x, y in zip(a, b)])
        g = jax.vmap(fl.fp_mul)
        check_batch(g(to_dev(a), to_dev(b)), [x * y % P for x, y in zip(a, b)])
