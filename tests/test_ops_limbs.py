"""Differential tests: ops.limbs (JAX 16-bit-limb Fq) vs the bigint oracle.

Strategy mirrors how the reference differential-tests its BLS backends
against each other (packages/beacon-node/test/spec/general/bls.ts runs the
same vectors through the facade): every kernel result is compared to
``lodestar_tpu.crypto.bls.fields`` on batches of random and adversarial
inputs.
"""

import secrets

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto.bls.fields import P
from lodestar_tpu.ops import limbs as fl


def rand_ints(n, bound=P):
    return [secrets.randbelow(bound) for _ in range(n)]


def adversarial_ints():
    """Edge values for carry/fold paths."""
    vals = [0, 1, 2, P - 1, P - 2, P, P + 1, (1 << 381) - 1, (1 << 384) - 1]
    # all-0xffff digit patterns and single-high-digit patterns
    vals.append((1 << fl.VALUE_BITS) - 1)
    vals.append(((1 << fl.VALUE_BITS) - 1) - 0xFFFF)
    for k in (0, 12, 24, 25):
        vals.append(0xFFFF << (16 * k))
    return [v % (1 << fl.VALUE_BITS) for v in vals]


def to_dev(ints):
    return jnp.asarray(fl.ints_to_limbs(ints))


def check_batch(arr, expected_ints):
    arr = np.asarray(arr)
    assert arr.shape[-1] == fl.NLIMBS
    for row, exp in zip(arr.reshape(-1, fl.NLIMBS), expected_ints):
        got = fl.limbs_to_int(row)
        # semi-strict representation: digits <= 2^8 (fixed point of the
        # branch-free folding carries), value < ~1.004 * 2^VALUE_BITS
        assert got < (1 << (fl.VALUE_BITS + 1)), "strict invariant violated (value)"
        assert np.all(row <= (1 << fl.LIMB_BITS)), "strict invariant violated (loose digit)"
        assert got % P == exp % P, f"mod-p mismatch: got {hex(got)} want {hex(exp % P)}"


class TestPacking:
    def test_roundtrip(self):
        for v in rand_ints(20, 1 << fl.VALUE_BITS) + adversarial_ints():
            assert fl.limbs_to_int(fl.int_to_limbs(v)) == v

    def test_out_of_range(self):
        with pytest.raises(ValueError):
            fl.int_to_limbs(1 << fl.VALUE_BITS)
        with pytest.raises(ValueError):
            fl.int_to_limbs(-1)

    def test_batch_matches_scalar(self):
        # the vectorized byte->limb path (TpuBlsVerifier packing hot path)
        # is bit-identical to the per-digit scalar reference
        vals = rand_ints(20, 1 << fl.VALUE_BITS) + adversarial_ints()
        got = fl.ints_to_limbs(vals)
        want = np.stack([fl.int_to_limbs(v) for v in vals])
        assert got.dtype == want.dtype and (got == want).all()
        assert fl.ints_to_limbs([]).shape == (0, fl.NLIMBS)

    def test_batch_out_of_range(self):
        with pytest.raises(ValueError):
            fl.ints_to_limbs([1, 1 << fl.VALUE_BITS])
        with pytest.raises(ValueError):
            fl.ints_to_limbs([-1])


class TestRing:
    def test_add_strict_chain(self):
        # chains of lazy adds then one fp_strict
        a, b, c, d = (rand_ints(64, 1 << fl.VALUE_BITS) for _ in range(4))
        out = fl.fp_strict(fl.fp_add(fl.fp_add(to_dev(a), to_dev(b)), fl.fp_add(to_dev(c), to_dev(d))))
        check_batch(out, [w + x + y + z for w, x, y, z in zip(a, b, c, d)])

    def test_sub(self):
        a, b = rand_ints(64, 1 << fl.VALUE_BITS), rand_ints(64, 1 << fl.VALUE_BITS)
        out = fl.fp_sub(to_dev(a), to_dev(b))
        check_batch(out, [(x - y) % P for x, y in zip(a, b)])

    def test_sub_loose_inputs(self):
        # minuend loose from a 4-add chain; subtrahend loose from one add
        a, b, c, d = (rand_ints(32, 1 << fl.VALUE_BITS) for _ in range(4))
        minuend = fl.fp_add(fl.fp_add(to_dev(a), to_dev(b)), to_dev(c))  # digits < 3*2^16 < 2^18
        subtrahend = fl.fp_add(to_dev(d), to_dev(a))  # digits < 2^17 < 2^20 bound
        out = fl.fp_sub(minuend, subtrahend)
        check_batch(out, [(x + y + z - (w + x)) % P for x, y, z, w in zip(a, b, c, d)])

    def test_neg(self):
        a = rand_ints(32, 1 << fl.VALUE_BITS) + adversarial_ints()
        out = fl.fp_neg(to_dev(a))
        check_batch(out, [(-x) % P for x in a])

    def test_mul_random(self):
        a, b = rand_ints(128, 1 << fl.VALUE_BITS), rand_ints(128, 1 << fl.VALUE_BITS)
        out = fl.fp_mul(to_dev(a), to_dev(b))
        check_batch(out, [x * y % P for x, y in zip(a, b)])

    def test_mul_adversarial(self):
        adv = adversarial_ints()
        a = adv * len(adv)
        b = [v for v in adv for _ in adv]
        out = fl.fp_mul(to_dev(a), to_dev(b))
        check_batch(out, [x * y % P for x, y in zip(a, b)])

    def test_mul_loose_flag(self):
        a, b, c = rand_ints(16, 1 << fl.VALUE_BITS), rand_ints(16, 1 << fl.VALUE_BITS), rand_ints(16, 1 << fl.VALUE_BITS)
        loose = fl.fp_add(to_dev(a), to_dev(b))
        out = fl.fp_mul(loose, to_dev(c), a_strict=False)
        check_batch(out, [(x + y) * z % P for x, y, z in zip(a, b, c)])

    def test_mul_small(self):
        a = rand_ints(32, 1 << fl.VALUE_BITS) + adversarial_ints()
        for k in (0, 1, 2, 3, 8, 12, (1 << 14) - 1):
            out = fl.fp_mul_small(to_dev(a), k)
            check_batch(out, [x * k % P for x in a])

    def test_batch_shapes(self):
        # leading axes broadcast: (2, 3) batch
        a = rand_ints(6)
        b = rand_ints(6)
        av = to_dev(a).reshape(2, 3, fl.NLIMBS)
        bv = to_dev(b).reshape(2, 3, fl.NLIMBS)
        out = np.asarray(fl.fp_mul(av, bv)).reshape(6, fl.NLIMBS)
        check_batch(out, [x * y % P for x, y in zip(a, b)])


class TestReduceCompare:
    def test_reduce_full(self):
        vals = rand_ints(64, 1 << fl.VALUE_BITS) + adversarial_ints()
        out = np.asarray(fl.fp_reduce_full(to_dev(vals)))
        for row, v in zip(out, vals):
            got = fl.limbs_to_int(row)
            assert got == v % P

    def test_eq(self):
        a = rand_ints(16)
        shifted = [(x + P) for x in a]  # same residue, different representation
        assert bool(jnp.all(fl.fp_eq(to_dev(a), to_dev(shifted))))
        b = [(x + 1) % P for x in a]
        assert not bool(jnp.any(fl.fp_eq(to_dev(a), to_dev(b))))

    def test_is_zero(self):
        vals = [0, P, 2 * P, 1, P - 1, 7 * P]
        out = np.asarray(fl.fp_is_zero(to_dev(vals)))
        assert list(out) == [True, True, True, False, False, True]


class TestPowInv:
    def test_pow_static(self):
        a = rand_ints(8)
        for e in (0, 1, 2, 3, 65537, P - 2):
            out = np.asarray(fl.fp_pow_static(to_dev(a), e))
            for row, x in zip(out, a):
                assert fl.limbs_to_int(row) % P == pow(x, e, P)

    def test_inv(self):
        a = [x for x in rand_ints(8) if x]
        out = np.asarray(fl.fp_inv(to_dev(a)))
        for row, x in zip(out, a):
            assert fl.limbs_to_int(row) % P == pow(x, P - 2, P)

    def test_inv_jit(self):
        a = [x for x in rand_ints(4) if x]
        f = jax.jit(fl.fp_inv)
        out = np.asarray(f(to_dev(a)))
        for row, x in zip(out, a):
            assert (fl.limbs_to_int(row) * x) % P == 1


class TestJit:
    def test_mul_under_jit_and_vmap(self):
        a, b = rand_ints(32), rand_ints(32)
        f = jax.jit(fl.fp_mul)
        check_batch(f(to_dev(a), to_dev(b)), [x * y % P for x, y in zip(a, b)])
        g = jax.vmap(fl.fp_mul)
        check_batch(g(to_dev(a), to_dev(b)), [x * y % P for x, y in zip(a, b)])


LIMB_MUL_MODES = fl._LIMB_MUL_MODES


class TestLimbMulModes:
    """Oracle-differential coverage of every limb-mul implementation
    (PR 18 MXU mapping): the VPU ladder, the MXU one-hot contraction,
    and the 9-bit re-packed variant are each held to the bigint-oracle
    ground truth at the same adversarial corners, to the strict/loose
    input contract, and (ladder vs mxu: bitwise) to each other."""

    @pytest.mark.parametrize("mode", LIMB_MUL_MODES)
    def test_mul_adversarial_all_pairs(self, mode):
        # 0, 1, p-1, the max-hamming 2^400-1 pattern, single-high-digit
        # spikes — every pair, through every implementation
        adv = adversarial_ints()
        a = adv * len(adv)
        b = [v for v in adv for _ in adv]
        out = fl.fp_mul(to_dev(a), to_dev(b), mode=mode)
        check_batch(out, [x * y % P for x, y in zip(a, b)])

    @pytest.mark.parametrize("mode", LIMB_MUL_MODES)
    def test_mul_strict_loose_mixes(self, mode):
        a = adversarial_ints()
        b = list(reversed(a))
        c = rand_ints(len(a), 1 << fl.VALUE_BITS)
        loose = fl.fp_add(to_dev(a), to_dev(b))  # digits past strict
        strict = to_dev(c)
        want_ab = [(x + y) % P for x, y in zip(a, b)]
        out = fl.fp_mul(loose, strict, a_strict=False, mode=mode)
        check_batch(out, [u * z % P for u, z in zip(want_ab, c)])
        out = fl.fp_mul(strict, loose, b_strict=False, mode=mode)
        check_batch(out, [u * z % P for u, z in zip(want_ab, c)])
        out = fl.fp_mul(loose, loose, a_strict=False, b_strict=False, mode=mode)
        check_batch(out, [u * u % P for u in want_ab])

    @pytest.mark.parametrize("mode", LIMB_MUL_MODES)
    def test_sqr_and_inv(self, mode):
        vals = [v for v in adversarial_ints() if v % P]
        sq = fl.fp_sqr(to_dev(vals), mode=mode)
        check_batch(sq, [v * v % P for v in vals])
        inv = np.asarray(fl.fp_inv(to_dev(vals), mode=mode))
        for row, v in zip(inv, vals):
            assert (fl.limbs_to_int(row) * v) % P == 1

    def test_ladder_and_mxu_agree_bitwise(self):
        # identical anti-diagonal sums in exact f32 arithmetic + the same
        # finalize: the two implementations must agree on the exact digit
        # representation, not just the residue
        a = rand_ints(32, 1 << fl.VALUE_BITS) + adversarial_ints()
        b = list(reversed(a))
        lad = np.asarray(fl.fp_mul(to_dev(a), to_dev(b), mode="ladder"))
        mxu = np.asarray(fl.fp_mul(to_dev(a), to_dev(b), mode="mxu"))
        assert np.array_equal(lad, mxu)
        # mxu9 finalizes from a different digit layout: same residue, not
        # necessarily the same redundant representation
        mxu9 = np.asarray(fl.fp_mul(to_dev(a), to_dev(b), mode="mxu9"))
        for r9, rl in zip(mxu9, lad):
            assert fl.limbs_to_int(r9) % P == fl.limbs_to_int(rl) % P

    def test_mode_selection(self, monkeypatch):
        monkeypatch.setenv("LODESTAR_TPU_LIMB_MUL", "mxu")
        assert fl.limb_mul_mode() == "mxu"
        monkeypatch.setenv("LODESTAR_TPU_LIMB_MUL", "LADDER")
        assert fl.limb_mul_mode() == "ladder"
        monkeypatch.delenv("LODESTAR_TPU_LIMB_MUL", raising=False)
        expect = "mxu" if jax.default_backend() == "tpu" else "ladder"
        assert fl.limb_mul_mode() == expect
        with pytest.raises(ValueError):
            fl.fp_mul(to_dev([1]), to_dev([2]), mode="simd")


@pytest.mark.slow
class TestRealKernelModeEquivalence:
    """The hash-to-G2 device kernel — a real consumer stacking thousands
    of fp_mul calls through the tower — compiled once per limb-mul mode.
    ladder and mxu must agree BITWISE end to end; the J.10 device
    vectors already pin the default path to the oracle, so this chain
    extends that pin to the MXU contraction."""

    def test_hash_to_g2_ladder_vs_mxu(self, monkeypatch):
        from lodestar_tpu.ops import htc

        msgs = [b"limb-mul-mode-equivalence-%d" % i for i in range(4)]
        u = jnp.asarray(htc.hash_to_field_limbs(msgs))
        raw = htc.hash_to_g2_device.__wrapped__
        outs = {}
        for mode in ("ladder", "mxu"):
            monkeypatch.setenv("LODESTAR_TPU_LIMB_MUL", mode)
            # a FRESH jit per mode: the module-level jit's cache key does
            # not carry the env var, so it must never straddle the flip
            outs[mode] = jax.tree_util.tree_leaves(jax.jit(raw)(u))
        assert outs["ladder"] and len(outs["ladder"]) == len(outs["mxu"])
        for cl, cm in zip(outs["ladder"], outs["mxu"]):
            assert np.array_equal(np.asarray(cl), np.asarray(cm))
