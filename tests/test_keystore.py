"""EIP-2335 keystore tests: official spec vectors + round-trips.

The two KAT keystores are the EIP-2335 specification's own test vectors
(scrypt and pbkdf2, same secret/password/salt/iv).
"""

import json

import pytest

from lodestar_tpu.validator.keystore import (
    KeystoreError,
    aes128_ctr,
    create_keystore,
    decrypt_keystore,
    load_keystores_dir,
)

EIP2335_PASSWORD = "\U0001d531\U0001d522\U0001d530\U0001d531\U0001d52d\U0001d51e\U0001d530\U0001d530\U0001d534\U0001d52c\U0001d52f\U0001d521\U0001f511"
EIP2335_SECRET = bytes.fromhex(
    "000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f"
)

SCRYPT_VECTOR = {
    "crypto": {
        "kdf": {
            "function": "scrypt",
            "params": {
                "dklen": 32, "n": 262144, "p": 1, "r": 8,
                "salt": "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3",
            },
            "message": "",
        },
        "checksum": {
            "function": "sha256", "params": {},
            "message": "d2217fe5f3e9a1e34581ef8a78f7c9928e436d36dacc5e846690a5581e8ea484",
        },
        "cipher": {
            "function": "aes-128-ctr",
            "params": {"iv": "264daa3f303d7259501c93d997d84fe6"},
            "message": "06ae90d55fe0a6e9c5c3bc5b170827b2e5cce3929ed3f116c2811e6366dfe20f",
        },
    },
    "description": "This is a test keystore that uses scrypt to secure the secret.",
    "pubkey": "9612d7a727c9d0a22e185a1c768478dfe919cada9266988cb32359c11f2b7b27f4ae4040902382ae2910c15e2b420d07",
    "path": "m/12381/60/3141592653/589793238",
    "version": 4,
}

PBKDF2_VECTOR = {
    "crypto": {
        "kdf": {
            "function": "pbkdf2",
            "params": {
                "dklen": 32, "c": 262144, "prf": "hmac-sha256",
                "salt": "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3",
            },
            "message": "",
        },
        "checksum": {
            "function": "sha256", "params": {},
            "message": "8a9f5d9912ed7e75ea794bc5a89bca5f193721d30868ade6f73043c6ea6febf1",
        },
        "cipher": {
            "function": "aes-128-ctr",
            "params": {"iv": "264daa3f303d7259501c93d997d84fe6"},
            "message": "cee03fde2af33149775b7223e7845e4fb2c8ae1792e5f99fe9ecf474cc8c16ad",
        },
    },
    "description": "This is a test keystore that uses PBKDF2 to secure the secret.",
    "pubkey": "9612d7a727c9d0a22e185a1c768478dfe919cada9266988cb32359c11f2b7b27f4ae4040902382ae2910c15e2b420d07",
    "path": "m/12381/60/0/0",
    "version": 4,
}


def test_aes128_ctr_fips_kat():
    # NIST SP 800-38A F.5.1 CTR-AES128.Encrypt, block 1
    key = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
    iv = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
    pt = bytes.fromhex("6bc1bee22e409f96e93d7e117393172a")
    assert aes128_ctr(key, iv, pt).hex() == "874d6191b620e3261bef6864990db6ce"


def test_eip2335_scrypt_vector():
    assert decrypt_keystore(SCRYPT_VECTOR, EIP2335_PASSWORD) == EIP2335_SECRET


def test_eip2335_pbkdf2_vector():
    assert decrypt_keystore(PBKDF2_VECTOR, EIP2335_PASSWORD) == EIP2335_SECRET


def test_wrong_password_rejected():
    with pytest.raises(KeystoreError, match="checksum"):
        decrypt_keystore(SCRYPT_VECTOR, "wrong")


def test_create_and_reload_roundtrip(tmp_path):
    secret = bytes(range(32))
    ks = create_keystore(secret, "hunter2hunter2", kdf="pbkdf2")
    assert decrypt_keystore(ks, "hunter2hunter2") == secret
    # directory loading (account-manager import flow)
    (tmp_path / "keystore-0.json").write_text(json.dumps(ks))
    loaded = load_keystores_dir(str(tmp_path), "hunter2hunter2")
    assert list(loaded.values()) == [secret]
    pk = next(iter(loaded))
    assert len(pk) == 48
