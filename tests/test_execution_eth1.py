"""Execution engine (mock + HTTP JSON-RPC) and eth1 tracker tests.

Reference flows: execution/engine/{http,mock}.ts,
eth1/eth1DepositDataTracker.ts.
"""

import asyncio
import json

import pytest

from lodestar_tpu.eth1 import Eth1DepositDataTracker, Eth1ProviderMock
from lodestar_tpu.execution import (
    DisabledExecutionEngine,
    ExecutePayloadStatus,
    ExecutionEngineHttp,
    ExecutionEngineMock,
)
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.ssz import Fields
from lodestar_tpu.state_transition.weak_subjectivity import (
    compute_weak_subjectivity_period,
    is_within_weak_subjectivity_period,
)


def test_engine_mock_payload_cycle():
    eng = ExecutionEngineMock(MINIMAL)
    pid = eng.notify_forkchoice_update(
        b"\x00" * 32, b"\x00" * 32, b"\x00" * 32,
        Fields(timestamp=12, prev_randao=b"\x01" * 32,
               suggested_fee_recipient=b"\x02" * 20),
    )
    assert pid is not None
    payload = eng.get_payload(pid)
    assert payload.block_number == 0
    assert eng.notify_new_payload(payload) == ExecutePayloadStatus.VALID
    # chain a second payload on top
    eng.notify_forkchoice_update(bytes(payload.block_hash), b"\x00" * 32, b"\x00" * 32,
                                 Fields(timestamp=24, prev_randao=b"\x03" * 32,
                                        suggested_fee_recipient=b"\x02" * 20))
    p2 = eng.get_payload(eng.payload_id_seq)
    assert p2.block_number == 1
    assert bytes(p2.parent_hash) == bytes(payload.block_hash)


def test_engine_disabled_raises():
    eng = DisabledExecutionEngine()
    with pytest.raises(RuntimeError):
        eng.notify_new_payload(None)


def test_engine_http_against_stub_server():
    async def main():
        seen = {}

        async def handle(reader, writer):
            data = await reader.read(65536)
            body = json.loads(data.split(b"\r\n\r\n", 1)[1])
            seen["method"] = body["method"]
            if body["method"] == "engine_newPayloadV1":
                result = {"status": "VALID", "latestValidHash": None}
            else:
                result = {"payloadStatus": {"status": "VALID"}, "payloadId": "0x01"}
            resp = json.dumps({"jsonrpc": "2.0", "id": body["id"], "result": result}).encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\ncontent-type: application/json\r\n"
                + b"content-length: %d\r\n\r\n" % len(resp) + resp
            )
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        eng = ExecutionEngineHttp("127.0.0.1", port, jwt_supplier=lambda: "token")
        payload = ExecutionEngineMock(MINIMAL)
        pid = payload.notify_forkchoice_update(
            b"\x00" * 32, b"\x00" * 32, b"\x00" * 32,
            Fields(timestamp=1, prev_randao=b"\x00" * 32,
                   suggested_fee_recipient=b"\x00" * 20),
        )
        p = payload.get_payload(pid)
        status = await eng.notify_new_payload(p)
        assert status == ExecutePayloadStatus.VALID
        assert seen["method"] == "engine_newPayloadV1"
        pid2 = await eng.notify_forkchoice_update(b"\x11" * 32, b"\x11" * 32, b"\x11" * 32)
        assert pid2 == 1
        server.close()
        await server.wait_closed()

    asyncio.run(main())


def test_eth1_tracker_votes_and_deposits():
    from lodestar_tpu.types import get_types

    t = get_types(MINIMAL).phase0
    provider = Eth1ProviderMock()
    tracker = Eth1DepositDataTracker(MINIMAL, provider)
    dd = Fields(
        pubkey=b"\x01" * 48, withdrawal_credentials=b"\x02" * 32,
        amount=32_000_000_000, signature=b"\x03" * 96,
    )
    provider.add_deposit(10, dd)
    provider.advance_to(3000)
    tracker.follow()
    assert tracker.deposit_count == 1

    # no period votes -> follow-distance snapshot
    state = t.BeaconState.default()
    vote = tracker.get_eth1_vote(state)
    assert vote.deposit_count == 1
    assert bytes(vote.block_hash) != b"\x00" * 32

    # majority vote wins when it can still reach >1/2 of the period
    leading = Fields(deposit_root=b"\x0a" * 32, deposit_count=5, block_hash=b"\x0b" * 32)
    state.eth1_data_votes = [leading] * (
        MINIMAL.EPOCHS_PER_ETH1_VOTING_PERIOD * MINIMAL.SLOTS_PER_EPOCH // 2 + 1
    )
    vote2 = tracker.get_eth1_vote(state)
    assert bytes(vote2.block_hash) == b"\x0b" * 32


def test_weak_subjectivity_period():
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.state_transition import interop_genesis_state

    cfg = ChainConfig(
        PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    )
    state = interop_genesis_state(MINIMAL, cfg, 16, 1)
    ws = compute_weak_subjectivity_period(MINIMAL, state)
    assert ws >= 256  # never below the withdrawability delay
    assert is_within_weak_subjectivity_period(MINIMAL, state, 0, ws)
    assert not is_within_weak_subjectivity_period(MINIMAL, state, 0, ws + 1)

    # raw balances above the 32 ETH cap must NOT inflate the period — the
    # formula is defined over effective balances (ADVICE r3): a state with
    # everyone holding 40 ETH raw but 32 effective gives the same period
    for i in range(len(state.balances)):
        state.balances[i] = 40 * 10**9
    assert compute_weak_subjectivity_period(MINIMAL, state) == ws

    # churn branch (t == T here) includes the balance-top-up floor:
    # max(churn_term, N*(200+3D)//(600*Delta)) can exceed the churn term
    # for huge N — sanity-check the term is wired by scaling N via a fake
    class _V:
        def __init__(self):
            self.activation_epoch = 0
            self.exit_epoch = 2**64 - 1
            self.effective_balance = 32 * 10**9

    class _S:
        slot = 0
        validators = [_V() for _ in range(200_000)]
        balances = [32 * 10**9] * 200_000

    big = compute_weak_subjectivity_period(MINIMAL, _S())
    D, delta_ = 10, max(4, 200_000 // 65536)
    churn_term = (200_000 * (32 * (200 + 120) - 32 * 230)) // (600 * delta_ * 96)
    topup_term = (200_000 * 230) // (600 * MINIMAL.MAX_DEPOSITS * MINIMAL.SLOTS_PER_EPOCH)
    assert big == 256 + max(churn_term, topup_term)
