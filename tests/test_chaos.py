"""Chaos fault plane + self-healing device pool (docs/chaos.md), on CPU.

Contracts pinned here:

1. The disarmed fault plane is INERT: seam sites read ``CHAOS.armed`` and
   nothing else — no draw, no lock, no context dict.
2. Fault plans are deterministic: seed + crossing order fully decide what
   fires (the campaign's repro guarantee).
3. Injected ``result()`` exceptions release the executor slot and resolve
   the in-flight table entry EXACTLY once (ISSUE 8 satellite: no leaked
   slot starving least-loaded placement, no double-release).
4. A lost device's batch is requeued onto a surviving executor before any
   per-job retry; the executor walks healthy -> suspect -> quarantined ->
   probe -> re-admitted; a fully-quarantined pool still serves.
5. The fused -> XLA -> native degradation ladder fires one
   ``bls_degrade_total{where,tier}`` increment + one ``bls.degrade``
   journal event per hop, end to end.
6. ``tools/check_trace.py`` accepts ``bls.requeue`` spans and demands the
   re-dispatch; ``tools/inspect_bundle.py`` surfaces the chaos triage
   section; the full campaign smoke (``tools/chaos_campaign.py``) holds
   the zero-undiagnosable-deaths guarantee.

Budget discipline (tests/conftest.py compile guard): every test injects
STUB device programs — the fault plane, health machine, requeue path and
forensics are all host-side.  Nothing here traces or compiles XLA
programs, and the module stays OUTSIDE the compile-guard whitelist.
"""

import json
import os
import time

import pytest

from lodestar_tpu import tracing
from lodestar_tpu.chaos import (
    CHAOS,
    DeviceLostError,
    FaultPlan,
    FaultSpec,
    install_from_env,
)
from lodestar_tpu.chaos.plan import PLAN_ENV, ChaosController, corrupt_file
from lodestar_tpu.crypto.bls.tpu_verifier import (
    HEALTHY,
    PROBING,
    QUARANTINED,
    SUSPECT,
)
from lodestar_tpu.forensics.journal import JOURNAL
from lodestar_tpu.forensics.recorder import RECORDER
from lodestar_tpu.forensics.watchdog import INFLIGHT
from lodestar_tpu.metrics import create_metrics
from lodestar_tpu.tracing import TRACER

from tools.chaos_campaign import make_sets, run_campaign, stub_verifier


@pytest.fixture(autouse=True)
def _clean_chaos():
    CHAOS.disarm()
    TRACER.disable()
    TRACER.clear()
    INFLIGHT.clear()
    yield
    CHAOS.disarm()
    TRACER.disable()
    TRACER.clear()
    INFLIGHT.clear()


def journal_since(seq0):
    return [e for e in JOURNAL.events() if e["seq"] >= seq0]


# ---------------------------------------------------------------------------
# 1+2. the fault plane itself
# ---------------------------------------------------------------------------


class TestFaultPlane:
    def test_disarmed_seams_never_reach_the_controller(self, monkeypatch):
        """Every seam site gates on the plain ``CHAOS.armed`` bool: with
        no plan armed, a poisoned fire()/maybe_raise() is never called
        across a full pack -> dispatch -> result cycle and a bundle
        write."""
        def poisoned(*a, **k):
            raise AssertionError("disarmed seam called into the controller")

        monkeypatch.setattr(CHAOS, "fire", poisoned)
        monkeypatch.setattr(CHAOS, "maybe_raise", poisoned)
        v = stub_verifier(n_devices=2)
        assert v.dispatch(v.pack(make_sets(2))).result() is True
        from lodestar_tpu.forensics.bundle import write_bundle

        write_bundle(str("/tmp/lodestar-chaos-disarmed-probe"), "probe")

    def test_plan_window_and_determinism(self):
        c1, c2 = ChaosController(), ChaosController()
        for c in (c1, c2):
            c.install(FaultPlan(
                seed=5,
                faults=[FaultSpec(seam="device.loss", after=1, count=2,
                                  probability=0.5)],
            ))
        pattern1 = [c1.fire("device.loss", device="d") is not None
                    for _ in range(12)]
        pattern2 = [c2.fire("device.loss", device="d") is not None
                    for _ in range(12)]
        assert pattern1 == pattern2          # same seed -> same firings
        assert pattern1[0] is False          # after=1 skips the first hit
        assert sum(pattern1) == 2            # count=2 bounds total firings
        c1.disarm()
        c2.disarm()

    def test_match_filters_context(self):
        c = ChaosController()
        c.install(FaultPlan(0).add("device.loss", match={"device": "cpu:1"}))
        assert c.fire("device.loss", device="cpu:0") is None
        assert c.fire("device.wedge", device="cpu:1") is None  # wrong seam
        assert c.fire("device.loss", device="cpu:1") is not None
        assert c.injected[-1]["ctx"]["device"] == "cpu:1"
        c.disarm()

    def test_install_from_env_round_trip(self, monkeypatch):
        plan = FaultPlan(3).add("bls.compile", match={"fused": True},
                                count=4, wedge_s=0.5)
        monkeypatch.setenv(PLAN_ENV, plan.to_json())
        assert install_from_env() is True
        assert CHAOS.armed
        state = CHAOS.state()
        assert state["seed"] == 3
        assert state["faults"][0]["seam"] == "bls.compile"
        assert state["faults"][0]["count"] == 4
        CHAOS.disarm()
        monkeypatch.setenv(PLAN_ENV, "{not json")
        assert install_from_env() is False
        assert not CHAOS.armed

    def test_corrupt_file_is_seed_deterministic(self, tmp_path):
        p = tmp_path / "entry.bin"
        p.write_bytes(bytes(range(256)))
        first = corrupt_file(str(p), seed=9)
        data1 = p.read_bytes()
        p.write_bytes(bytes(range(256)))
        assert corrupt_file(str(p), seed=9) == first
        assert p.read_bytes() == data1
        p.write_bytes(bytes(range(256)))
        assert p.read_bytes() != data1 or not first  # corruption happened


# ---------------------------------------------------------------------------
# 3. exactly-once release under injected result() exceptions
# ---------------------------------------------------------------------------


class TestExactlyOnceRelease:
    def test_raise_frees_slot_once_and_resolves_inflight(self):
        """A result() raise on a single-device pool (no survivor, no
        sets) must free the executor slot exactly once, resolve the
        in-flight table entry, and replay the SAME failure on re-calls
        (never a fresh sync that would silently succeed)."""
        v = stub_verifier(n_devices=1)
        CHAOS.install(FaultPlan(0).add("device.loss"))
        pend = v.dispatch(v.pack(make_sets(2)))  # sets=None: nothing to requeue to
        assert len(INFLIGHT) == 1
        with pytest.raises(DeviceLostError):
            pend.result()
        assert len(INFLIGHT) == 0, "in-flight entry not resolved on raise"
        assert v.device_inflight() == {"default": 0}, "slot not freed exactly once"
        with pytest.raises(DeviceLostError):
            pend.result()  # idempotent failure — no second sync, no double release
        assert v.device_inflight() == {"default": 0}
        assert len(INFLIGHT) == 0
        # the pool is not wedged: the next dispatch still serves
        CHAOS.disarm()
        assert v.dispatch(v.pack(make_sets(2, start=8))).result() is True

    def test_success_path_release_still_exactly_once(self):
        v = stub_verifier(n_devices=2)
        pend = v.dispatch(v.pack(make_sets(2)))
        assert pend.result() is True
        assert pend.result() is True
        assert all(n == 0 for n in v.device_inflight().values())
        assert len(INFLIGHT) == 0


# ---------------------------------------------------------------------------
# 4. requeue + quarantine + backoff re-admission
# ---------------------------------------------------------------------------


class TestSelfHealing:
    def test_lost_batch_requeued_to_survivor(self, tmp_path):
        RECORDER.configure(forensics_dir=str(tmp_path))
        metrics = create_metrics()
        v = stub_verifier(n_devices=3)
        v.metrics = metrics
        target = v._executors[0].name
        seq0 = JOURNAL.seq
        tracing.enable(4096)
        CHAOS.install(
            FaultPlan(0).add("device.loss", match={"device": target}, count=1)
        )
        pend = v.dispatch(v.pack(make_sets(2)), sets=make_sets(2))
        assert pend.device == target
        assert pend.result() is True  # verdict survived the loss
        CHAOS.disarm()
        events = journal_since(seq0)
        requeue = [e for e in events if e["kind"] == "bls.requeue"]
        assert requeue and requeue[0]["from_device"] == target
        assert v.batches_requeued == 1
        assert v.executor_health()[target]["state"] == SUSPECT
        # the requeue span names both ends
        spans = [s for s in TRACER.spans() if s.name == "bls.requeue"]
        assert spans and spans[0].args["from_device"] == target
        assert spans[0].args["to_device"] != target
        text = metrics.reg.expose().decode()
        assert "lodestar_bls_batch_requeues_total 1.0" in text

    def test_quarantine_then_backoff_probe_readmission(self, tmp_path):
        RECORDER.configure(forensics_dir=str(tmp_path))
        metrics = create_metrics()
        v = stub_verifier(n_devices=3, threshold=1, backoff_s=0.5)
        v.metrics = metrics
        target = v._executors[1].name
        seq0 = JOURNAL.seq
        CHAOS.install(
            FaultPlan(0).add("device.loss", match={"device": target}, count=1)
        )
        # drive batches until the target takes one and fails it
        for i in range(6):
            assert v.dispatch(v.pack(make_sets(2, start=4 * i)),
                              sets=make_sets(2, start=4 * i)).result() is True
            if v.executor_health()[target]["state"] == QUARANTINED:
                break
        assert v.executor_health()[target]["state"] == QUARANTINED
        # while quarantined (the 0.5s backoff comfortably outlasts these
        # sub-ms placements): nothing lands on it
        for i in range(4):
            pend = v.dispatch(v.pack(make_sets(2, start=40 + 4 * i)))
            assert pend.device != target
            assert pend.result() is True
        # backoff expires -> the next placements probe and re-admit it
        time.sleep(0.55)
        deadline = time.monotonic() + 5.0
        while (v.executor_health()[target]["state"] != HEALTHY
               and time.monotonic() < deadline):
            v.dispatch(v.pack(make_sets(2, start=80))).result()
        assert v.executor_health()[target]["state"] == HEALTHY
        CHAOS.disarm()
        events = journal_since(seq0)
        states = [e.get("state") for e in events if e["kind"] == "bls.health"
                  and e.get("device") == target]
        assert QUARANTINED in states and PROBING in states
        assert any(e.get("readmitted") for e in events
                   if e["kind"] == "bls.health" and e.get("device") == target)
        text = metrics.reg.expose().decode()
        assert (f'lodestar_bls_device_quarantines_total{{device="{target}"}} 1.0'
                in text)
        # quarantine entry wrote a rate-limited bundle with the health map
        bundles = [n for n in os.listdir(tmp_path) if n.startswith("bundle-quarantine")]
        assert bundles, "no quarantine bundle written"

    def test_failed_probe_doubles_backoff(self):
        v = stub_verifier(n_devices=2, threshold=1, backoff_s=0.05)
        target = v._executors[0].name
        ex = v._executors[0]
        CHAOS.install(
            FaultPlan(0).add("device.loss", match={"device": target}, count=2)
        )
        # first failure -> quarantine at base backoff
        while v.executor_health()[target]["state"] != QUARANTINED:
            v.dispatch(v.pack(make_sets(2)), sets=make_sets(2)).result()
        assert ex.health.backoff_s == pytest.approx(0.05)
        time.sleep(0.07)
        # probe fails (second injected loss) -> re-quarantined, doubled
        deadline = time.monotonic() + 5.0
        while ex.health.quarantines < 2 and time.monotonic() < deadline:
            v.dispatch(v.pack(make_sets(2)), sets=make_sets(2)).result()
        assert ex.health.quarantines == 2
        assert ex.health.backoff_s == pytest.approx(0.1)
        CHAOS.disarm()

    def test_fully_quarantined_pool_still_serves(self):
        v = stub_verifier(n_devices=2, threshold=1, backoff_s=30.0)
        CHAOS.install(FaultPlan(0).add("device.loss", count=2))
        # quarantine both executors (requeue of the first loss lands on the
        # second and is lost too -> native tier resolves the verdict)
        pend = v.dispatch(v.pack(make_sets(2)), sets=make_sets(2))
        assert pend.result() is True
        states = {h["state"] for h in v.executor_health().values()}
        assert states == {QUARANTINED}
        assert v.native_fallbacks >= 1
        CHAOS.disarm()
        # a fully-sick pool degrades, it never deadlocks
        assert v.dispatch(v.pack(make_sets(2, start=8))).result() is True


# ---------------------------------------------------------------------------
# 5. the degradation ladder
# ---------------------------------------------------------------------------


class TestDegradationLadder:
    def test_full_ladder_one_event_and_increment_per_hop(self, tmp_path):
        RECORDER.configure(forensics_dir=str(tmp_path))
        metrics = create_metrics()
        v = stub_verifier(n_devices=2, fused=True)
        v.metrics = metrics
        seq0 = JOURNAL.seq
        CHAOS.install(
            FaultPlan(0)
            .add("bls.compile", match={"where": "dispatch", "fused": True}, count=1)
            .add("bls.compile", match={"where": "dispatch", "fused": False}, count=1)
        )
        pend = v.verify_signature_sets_async(make_sets(2))
        assert pend.result() is True
        assert pend.device == "native"
        CHAOS.disarm()
        tiers = [e.get("tier") for e in journal_since(seq0)
                 if e["kind"] == "bls.degrade"]
        assert tiers == ["xla", "native"]
        text = metrics.reg.expose().decode()
        assert 'lodestar_bls_degrade_total{tier="xla",where="dispatch"} 1.0' in text
        assert 'lodestar_bls_degrade_total{tier="native",where="dispatch"} 1.0' in text
        assert v.fused is False and v.native_fallbacks == 1
        # the XLA tier serves the next batch (faults exhausted)
        assert v.verify_signature_sets_async(make_sets(2, start=8)).result() is True
        # the native hop left a triageable bundle behind
        assert any(n.startswith("bundle-degrade-native")
                   for n in os.listdir(tmp_path))

    def test_warmup_compile_fault_degrades_without_real_compiles(self):
        """An injected warmup compile failure walks fused->XLA without
        ever reaching a real backend compile (both paths injected — the
        compile guard proves no program was built)."""
        metrics = create_metrics()
        v = stub_verifier(n_devices=1, fused=True)
        v.metrics = metrics
        seq0 = JOURNAL.seq
        CHAOS.install(
            FaultPlan(0)
            .add("bls.compile", match={"where": "warmup", "fused": True}, count=0)
            .add("bls.compile", match={"where": "warmup", "fused": False}, count=0)
        )
        # bucket 6 exists in no stub/compiled/memo cache: if the injection
        # missed, warmup would attempt a REAL compile and the conftest
        # guard would fail this test
        v.warmup(buckets=(6,))
        CHAOS.disarm()
        assert v.fused is False
        degrades = [e for e in journal_since(seq0) if e["kind"] == "bls.degrade"]
        assert [e.get("tier") for e in degrades] == ["xla"]
        assert degrades[0]["where"] == "warmup"
        text = metrics.reg.expose().decode()
        assert 'lodestar_bls_degrade_total{tier="xla",where="warmup"} 1.0' in text


# ---------------------------------------------------------------------------
# 6. tooling: check_trace requeue rule, inspect_bundle chaos triage,
#    campaign smoke
# ---------------------------------------------------------------------------


from tools.chaos_campaign import load_tool as _load_tool


def _span(name, cid, dur=5.0, **args):
    return {"name": name, "ph": "X", "ts": 0, "dur": dur, "pid": 1, "tid": 1,
            "args": dict(args, cid=cid)}


class TestCheckTraceRequeue:
    def _base_trace(self):
        events = []
        for cid in (1, 2):
            events += [
                _span("bls.queue_wait", cid),
                _span("bls.pack", cid),
                _span("bls.dispatch", cid, device="cpu:0", devices_total=2),
                _span("bls.final_exp", cid),
            ]
        return events

    def test_requeued_cid_passes_with_redispatch(self):
        check_trace = _load_tool("check_trace")
        events = self._base_trace()
        events += [
            _span("bls.requeue", 1, from_device="cpu:0", to_device="cpu:1"),
            _span("bls.dispatch", 1, device="cpu:1", devices_total=2),
        ]
        assert check_trace.validate_pipeline(events, 2) == []

    def test_requeue_without_redispatch_fails(self):
        check_trace = _load_tool("check_trace")
        events = self._base_trace()
        events.append(
            _span("bls.requeue", 2, from_device="cpu:0", to_device="cpu:1")
        )
        # give cid 1 a second device so the multi-device gate stays green
        events.append(_span("bls.dispatch", 1, device="cpu:1", devices_total=2))
        errors = check_trace.validate_pipeline(events, 2)
        assert any("requeue" in e and "cid 2" in e for e in errors), errors

    def test_real_requeued_run_passes_require_pipeline(self, tmp_path):
        """End to end: a pool-driven run with an injected device loss
        produces a dump that check_trace --require-pipeline accepts."""
        import asyncio

        from lodestar_tpu.chain.bls_pool import BlsBatchPool

        check_trace = _load_tool("check_trace")
        tracing.enable(8192)
        v = stub_verifier(n_devices=3)
        target = v._executors[0].name
        CHAOS.install(
            FaultPlan(0).add("device.loss", match={"device": target}, count=1)
        )
        pool = BlsBatchPool(v, max_buffer_wait=0.002, flush_threshold=4,
                            pipeline_depth=2)

        async def main():
            jobs = [
                asyncio.create_task(
                    pool.verify_signature_sets(make_sets(2, start=4 * i))
                )
                for i in range(6)
            ]
            return await asyncio.gather(*jobs)

        assert asyncio.run(main()) == [True] * 6
        CHAOS.disarm()
        pool.close()
        path = str(tmp_path / "requeue_trace.json")
        tracing.write_chrome_trace(TRACER, path)
        assert check_trace.main([path, "--require-pipeline", "2"]) == 0
        requeues = [s for s in TRACER.spans() if s.name == "bls.requeue"]
        assert requeues, "the injected loss never produced a requeue span"


class TestInspectBundleChaosTriage:
    def test_summary_names_fault_health_and_requeues(self, tmp_path):
        inspect_bundle = _load_tool("inspect_bundle")
        RECORDER.configure(forensics_dir=str(tmp_path))
        v = stub_verifier(n_devices=2, threshold=1, backoff_s=5.0)
        RECORDER.configure(verifier=v)
        target = v._executors[1].name
        CHAOS.install(
            FaultPlan(11).add("device.loss", match={"device": target}, count=1)
        )
        for i in range(4):
            v.dispatch(v.pack(make_sets(2, start=4 * i)),
                       sets=make_sets(2, start=4 * i)).result()
            if v.executor_health()[target]["state"] == QUARANTINED:
                break
        path = RECORDER.dump("chaos-triage-probe")
        CHAOS.disarm()
        assert inspect_bundle.validate(path) == []
        s = inspect_bundle.summarize(path)
        ch = s["chaos"]
        assert ch["armed"] is True and ch["seed"] == 11
        assert ch["last_fault"]["seam"] == "device.loss"
        assert ch["requeued_batches"] >= 1
        assert ch["executor_health"][target]["state"] == QUARANTINED
        timeline_states = [e["state"] for e in ch["health_timeline"]]
        assert QUARANTINED in timeline_states
        # the text renderer prints the section without blowing up
        inspect_bundle._print_text(s)


class TestCampaignSmoke:
    def test_campaign_fast_holds_the_guarantee(self, tmp_path):
        """The acceptance gate, tier-1 sized: every fault class yields a
        valid bundle, zero verdicts lost, pool back to healthy, 10%
        throughput recovery."""
        report = run_campaign(seed=0, out_dir=str(tmp_path), fast=True)
        assert report["failures"] == {}, json.dumps(report["failures"], indent=1)
        assert report["ok"] is True
        assert report["verdicts_lost"] == 0
        assert report["bundles_validated"] >= 6
        assert report["time_to_quarantine_s"] is not None
        assert report["time_to_recover_s"] is not None
