"""Differential tests for the fused G2 ladder-iteration kernels
(ops/fused_ladder.py) against the composed path (fused_points) and the
bigint oracle — interpret mode (CPU), small shapes.

Slow-marked by the PR 15 compile-cost audit: the three ladder programs
re-lower every run (~140 s of tier-1 wall, 8 compile-guard events in the
run ledger) and the fused path's tier-1 pin is test_fused_verify_alignment;
ladder ground truth runs in the nightly ``-m slow`` tier.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax.numpy as jnp

from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2
from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops.fused_core import f_canon, lv
from lodestar_tpu.ops.fused_ladder import point_mul_bits_ladder
from lodestar_tpu.ops.fused_points import (
    fq2_ns,
    point_eq,
    point_from_affine,
    point_mul_bits,
)


def _fq2_arr(e):
    c0 = e.c0.n if hasattr(e.c0, "n") else int(e.c0)
    c1 = e.c1.n if hasattr(e.c1, "n") else int(e.c1)
    return np.stack([fl.int_to_limbs(c0), fl.int_to_limbs(c1)])


def _points(n):
    return [hash_to_g2(bytes([i]) * 32) for i in range(n)]


def test_fused_ladder_matches_composed_path():
    ns = fq2_ns(True)
    pts = _points(3)
    aff = [p.to_affine() for p in pts]
    xs = jnp.asarray(np.stack([_fq2_arr(a[0]) for a in aff]))
    ys = jnp.asarray(np.stack([_fq2_arr(a[1]) for a in aff]))
    P = point_from_affine(lv(xs), lv(ys), ns)
    scalars = [11, 0, 6]
    nb = 5
    bits = jnp.asarray(
        np.array([[(s >> i) & 1 for i in range(nb)] for s in scalars], np.float32)
    )
    old = point_mul_bits(P, bits, ns, complete=True, interpret=True)
    new = point_mul_bits_ladder(P, bits, ns, interpret=True)
    assert np.array(point_eq(old, new, ns, True)).all()


def test_fused_ladder_ground_truth_and_infinity():
    ns = fq2_ns(True)
    p = _points(1)[0]
    ax, ay = p.to_affine()
    P = point_from_affine(
        lv(jnp.asarray(_fq2_arr(ax))[None]), lv(jnp.asarray(_fq2_arr(ay))[None]), ns
    )
    for s in (1, 2, 13):
        nb = max(1, s.bit_length())
        bits = jnp.asarray(np.array([[(s >> i) & 1 for i in range(nb)]], np.float32))
        out = point_mul_bits_ladder(P, bits, ns, interpret=True)
        want = p * s
        wx, wy = want.to_affine()
        Q = point_from_affine(
            lv(jnp.asarray(_fq2_arr(wx))[None]),
            lv(jnp.asarray(_fq2_arr(wy))[None]),
            ns,
        )
        assert bool(np.array(point_eq(out, Q, ns, True))[0]), f"scalar {s}"
    # zero scalar -> infinity (canonical z == 0)
    bits = jnp.asarray(np.zeros((1, 3), np.float32))
    out = point_mul_bits_ladder(P, bits, ns, interpret=True)
    assert (np.array(f_canon(out[2], True)) == 0).all()


def test_fused_ladder_multi_lane_lead_shape():
    """The merged-ladder (lanes, sets, ...) layout round-trips."""
    ns = fq2_ns(True)
    p = _points(1)[0]
    ax, ay = p.to_affine()
    xa = jnp.broadcast_to(jnp.asarray(_fq2_arr(ax))[None, None], (2, 1, 2, 50))
    ya = jnp.broadcast_to(jnp.asarray(_fq2_arr(ay))[None, None], (2, 1, 2, 50))
    P = point_from_affine(lv(xa), lv(ya), ns)
    bits = jnp.asarray(np.array([[[1, 1, 0]], [[0, 1, 1]]], np.float32))  # 3 and 6
    out = point_mul_bits_ladder(P, bits, ns, interpret=True)
    assert out[0].a.shape == (2, 1, 2, 50)
    for lane, s in ((0, 3), (1, 6)):
        want = p * s
        wx, wy = want.to_affine()
        Q = point_from_affine(
            lv(jnp.asarray(_fq2_arr(wx))[None]),
            lv(jnp.asarray(_fq2_arr(wy))[None]),
            ns,
        )
        sub = tuple(type(c)(c.a[lane], c.b) for c in out)
        assert bool(np.array(point_eq(sub, Q, ns, True))[0]), f"lane {lane}"
