"""Keymanager API: list/import/delete with slashing-protection
interchange (packages/api/src/keymanager/routes.ts; VERDICT r3 missing
item 10)."""

import asyncio
import json

from lodestar_tpu.api.client import ApiClient, ApiClientError
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.validator import SlashingProtection, ValidatorStore
from lodestar_tpu.validator.keymanager import KeymanagerApi, KeymanagerServer
from lodestar_tpu.validator.keystore import create_keystore

CFG = ChainConfig(PRESET_BASE="minimal", MIN_GENESIS_TIME=0,
                  SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16)


def _store(indices=(0, 1)):
    protection = SlashingProtection()
    keys = {i: interop_secret_key(i) for i in indices}
    return ValidatorStore(MINIMAL, CFG, keys, protection), protection


def test_keymanager_over_http_with_auth():
    async def main():
        store, protection = _store()
        api = KeymanagerApi(store, protection)
        srv = KeymanagerServer(api, token="s3cret")
        port = await srv.listen(0)
        client = ApiClient("127.0.0.1", port)

        # unauthenticated -> 401
        try:
            await client.get("/eth/v1/keystores")
            raise AssertionError("auth not enforced")
        except ApiClientError as e:
            assert e.status == 401

        # authenticated via raw request with the bearer header
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        req = (
            "GET /eth/v1/keystores HTTP/1.1\r\nhost: x\r\n"
            "authorization: Bearer s3cret\r\ncontent-length: 0\r\n\r\n"
        ).encode()
        writer.write(req)
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        assert status == 200
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
        body = json.loads(await reader.read())
        assert len(body["data"]) == 2
        writer.close()
        await srv.close()

    asyncio.run(main())


def test_import_and_delete_roundtrip():
    store, protection = _store(indices=(0,))
    api = KeymanagerApi(store, protection)

    # import validator 7's key from an EIP-2335 keystore + interchange
    sk7 = interop_secret_key(7)
    ks = create_keystore(sk7.to_bytes(), "pw", kdf="pbkdf2")
    pk7 = sk7.to_public_key().to_bytes()
    prior = SlashingProtection()
    prior.check_and_insert_attestation(pk7, 3, 4, b"\xaa" * 32)
    out = api.import_keystores(
        {
            "keystores": [json.dumps(ks)],
            "passwords": ["pw"],
            "slashing_protection": json.dumps(prior.export_interchange()),
        }
    )
    assert out["data"][0]["status"] == "imported"
    assert pk7 in store.pubkeys.values()
    # the imported history protects immediately
    import pytest

    from lodestar_tpu.validator.slashing_protection import SlashingError

    with pytest.raises(SlashingError):
        protection.check_and_insert_attestation(pk7, 3, 4, b"\xbb" * 32)

    # duplicate import reports duplicate
    again = api.import_keystores({"keystores": [json.dumps(ks)], "passwords": ["pw"]})
    assert again["data"][0]["status"] == "duplicate"
    # wrong password reports error
    bad = api.import_keystores({"keystores": [json.dumps(ks)], "passwords": ["nope"]})
    assert bad["data"][0]["status"] == "error"

    # delete returns the interchange and removes the key
    deleted = api.delete_keystores({"pubkeys": ["0x" + pk7.hex()]})
    assert deleted["data"][0]["status"] == "deleted"
    assert pk7 not in store.pubkeys.values()
    interchange = json.loads(deleted["slashing_protection"])
    assert any(e["pubkey"] == "0x" + pk7.hex() for e in interchange["data"])
    # deleting again -> not_found
    again2 = api.delete_keystores({"pubkeys": ["0x" + pk7.hex()]})
    assert again2["data"][0]["status"] == "not_found"
