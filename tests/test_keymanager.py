"""Keymanager API: list/import/delete with slashing-protection
interchange (packages/api/src/keymanager/routes.ts; VERDICT r3 missing
item 10)."""

import asyncio
import json

from lodestar_tpu.api.client import ApiClient, ApiClientError
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.validator import SlashingProtection, ValidatorStore
from lodestar_tpu.validator.keymanager import KeymanagerApi, KeymanagerServer
from lodestar_tpu.validator.keystore import create_keystore

CFG = ChainConfig(PRESET_BASE="minimal", MIN_GENESIS_TIME=0,
                  SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16)


def _store(indices=(0, 1)):
    protection = SlashingProtection()
    keys = {i: interop_secret_key(i) for i in indices}
    return ValidatorStore(MINIMAL, CFG, keys, protection), protection


def test_keymanager_over_http_with_auth():
    async def main():
        store, protection = _store()
        api = KeymanagerApi(store, protection)
        srv = KeymanagerServer(api, token="s3cret")
        port = await srv.listen(0)
        client = ApiClient("127.0.0.1", port)

        # unauthenticated -> 401
        try:
            await client.get("/eth/v1/keystores")
            raise AssertionError("auth not enforced")
        except ApiClientError as e:
            assert e.status == 401

        # authenticated via raw request with the bearer header
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        req = (
            "GET /eth/v1/keystores HTTP/1.1\r\nhost: x\r\n"
            "authorization: Bearer s3cret\r\ncontent-length: 0\r\n\r\n"
        ).encode()
        writer.write(req)
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        assert status == 200
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
        body = json.loads(await reader.read())
        assert len(body["data"]) == 2
        writer.close()
        await srv.close()

    asyncio.run(main())


def test_import_and_delete_roundtrip():
    store, protection = _store(indices=(0,))
    api = KeymanagerApi(store, protection)

    # import validator 7's key from an EIP-2335 keystore + interchange
    sk7 = interop_secret_key(7)
    ks = create_keystore(sk7.to_bytes(), "pw", kdf="pbkdf2")
    pk7 = sk7.to_public_key().to_bytes()
    prior = SlashingProtection()
    prior.check_and_insert_attestation(pk7, 3, 4, b"\xaa" * 32)
    out = api.import_keystores(
        {
            "keystores": [json.dumps(ks)],
            "passwords": ["pw"],
            "slashing_protection": json.dumps(prior.export_interchange()),
        }
    )
    assert out["data"][0]["status"] == "imported"
    assert pk7 in store.pubkeys.values()
    # the imported history protects immediately
    import pytest

    from lodestar_tpu.validator.slashing_protection import SlashingError

    with pytest.raises(SlashingError):
        protection.check_and_insert_attestation(pk7, 3, 4, b"\xbb" * 32)

    # duplicate import reports duplicate
    again = api.import_keystores({"keystores": [json.dumps(ks)], "passwords": ["pw"]})
    assert again["data"][0]["status"] == "duplicate"
    # wrong password reports error
    bad = api.import_keystores({"keystores": [json.dumps(ks)], "passwords": ["nope"]})
    assert bad["data"][0]["status"] == "error"

    # delete returns the interchange and removes the key
    deleted = api.delete_keystores({"pubkeys": ["0x" + pk7.hex()]})
    assert deleted["data"][0]["status"] == "deleted"
    assert pk7 not in store.pubkeys.values()
    interchange = json.loads(deleted["slashing_protection"])
    assert any(e["pubkey"] == "0x" + pk7.hex() for e in interchange["data"])
    # deleting again -> not_found
    again2 = api.delete_keystores({"pubkeys": ["0x" + pk7.hex()]})
    assert again2["data"][0]["status"] == "not_found"


def test_remotekeys_crud():
    """remotekeys namespace (keymanager routes.ts remote-key CRUD): import
    registers signer-backed pubkeys, list shows only non-local keys,
    delete removes them."""
    from lodestar_tpu.validator.remote_signer import RemoteSignerClient

    store, protection = _store(indices=(0,))
    store.remote_signer = RemoteSignerClient("http://127.0.0.1:9999")
    api = KeymanagerApi(store, protection)

    pk2 = interop_secret_key(2).to_public_key().to_bytes()
    out = api.import_remote_keys(
        {"remote_keys": [{"pubkey": "0x" + pk2.hex(), "url": "http://127.0.0.1:9999"}]}
    )
    assert out["data"][0]["status"] == "imported"
    # duplicate import reports duplicate
    out = api.import_remote_keys({"remote_keys": [{"pubkey": "0x" + pk2.hex()}]})
    assert out["data"][0]["status"] == "duplicate"

    listing = api.list_remote_keys()
    assert [e["pubkey"] for e in listing["data"]] == ["0x" + pk2.hex()]
    # the local keystore key is NOT a remote key
    assert all(
        e["pubkey"] != "0x" + store.pubkeys[0].hex() for e in listing["data"]
    )

    out = api.delete_remote_keys({"pubkeys": ["0x" + pk2.hex()]})
    assert out["data"][0]["status"] == "deleted"
    assert api.list_remote_keys()["data"] == []
    # deleting a local (keystore) key via remotekeys is not_found
    out = api.delete_remote_keys({"pubkeys": ["0x" + store.pubkeys[0].hex()]})
    assert out["data"][0]["status"] == "not_found"


def test_import_remote_key_without_signer_errors():
    store, protection = _store(indices=(0,))
    api = KeymanagerApi(store, protection)
    pk = interop_secret_key(5).to_public_key().to_bytes()
    out = api.import_remote_keys({"remote_keys": [{"pubkey": "0x" + pk.hex()}]})
    assert out["data"][0]["status"] == "error"


def test_fee_recipient_and_gas_limit_routes():
    """Per-validator feerecipient/gas_limit overrides with VC defaults
    (keymanager routes.ts listFeeRecipient/setFeeRecipient/...)."""

    class FakeClient:
        fee_recipient = b"\xaa" * 20
        gas_limit = 25_000_000
        fee_recipient_overrides = {}
        gas_limit_overrides = {}

    store, protection = _store(indices=(0,))
    api = KeymanagerApi(store, protection, client=FakeClient())
    pk_hex = "0x" + store.pubkeys[0].hex()

    # default from the client
    assert api.get_fee_recipient(pk_hex)["data"]["ethaddress"] == "0x" + "aa" * 20
    assert api.get_gas_limit(pk_hex)["data"]["gas_limit"] == "25000000"
    # override + delete
    api.set_fee_recipient(pk_hex, {"ethaddress": "0x" + "bb" * 20})
    assert api.get_fee_recipient(pk_hex)["data"]["ethaddress"] == "0x" + "bb" * 20
    api.delete_fee_recipient(pk_hex)
    assert api.get_fee_recipient(pk_hex)["data"]["ethaddress"] == "0x" + "aa" * 20
    api.set_gas_limit(pk_hex, {"gas_limit": "31000000"})
    assert api.get_gas_limit(pk_hex)["data"]["gas_limit"] == "31000000"


def test_overrides_drive_client_and_placeholders_never_collide():
    """Review fixes: (1) feerecipient/gas_limit POSTs must reach the
    ValidatorClient services, not just the GET routes; (2) placeholder
    indices stay unique across import/delete cycles."""
    from lodestar_tpu.validator.remote_signer import RemoteSignerClient

    class FakeClient:
        fee_recipient = b"\xaa" * 20
        gas_limit = 30_000_000
        fee_recipient_overrides = {}
        gas_limit_overrides = {}

    store, protection = _store(indices=(0,))
    store.remote_signer = RemoteSignerClient("http://127.0.0.1:9999")
    client = FakeClient()
    api = KeymanagerApi(store, protection, client=client)

    pk_hex = "0x" + store.pubkeys[0].hex()
    api.set_fee_recipient(pk_hex, {"ethaddress": "0x" + "cc" * 20})
    assert client.fee_recipient_overrides[store.pubkeys[0]] == b"\xcc" * 20
    api.set_gas_limit(pk_hex, {"gas_limit": "31000000"})
    assert client.gas_limit_overrides[store.pubkeys[0]] == 31_000_000
    api.delete_fee_recipient(pk_hex)
    api.delete_gas_limit(pk_hex)
    assert store.pubkeys[0] not in client.fee_recipient_overrides
    assert store.pubkeys[0] not in client.gas_limit_overrides

    # placeholder collision regression: import A, B; delete A; import C —
    # B must survive
    def pk(i):
        return "0x" + interop_secret_key(i).to_public_key().to_bytes().hex()

    api.import_remote_keys({"remote_keys": [{"pubkey": pk(2)}]})
    api.import_remote_keys({"remote_keys": [{"pubkey": pk(3)}]})
    api.delete_remote_keys({"pubkeys": [pk(2)]})
    api.import_remote_keys({"remote_keys": [{"pubkey": pk(4)}]})
    listed = {e["pubkey"] for e in api.list_remote_keys()["data"]}
    assert listed == {pk(3), pk(4)}
