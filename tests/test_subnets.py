"""Attnets/syncnets services + metadata rotation (attnetsService.ts:31,
network/metadata.ts; SURVEY component 28)."""
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier

import asyncio

from lodestar_tpu.network.subnets import (
    EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION,
    AttnetsService,
    MetadataController,
    SyncnetsService,
)
from lodestar_tpu.params import MINIMAL


def test_long_lived_subnets_rotate_and_bump_metadata():
    md = MetadataController()
    svc = AttnetsService(MINIMAL, md, node_seed=b"\x01" * 8)
    assert md.seq_number == 0
    svc.add_validator(5)
    assert md.seq_number == 1
    assert len(svc.active_subnets()) == 1
    first = svc.active_subnets()
    # stable within the subscription period
    svc.on_slot(10 * MINIMAL.SLOTS_PER_EPOCH)
    assert svc.active_subnets() == first
    # rotates across a full period boundary for this validator
    rotated = False
    for epochs in range(0, 3 * EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION, 16):
        svc.on_slot(epochs * MINIMAL.SLOTS_PER_EPOCH)
        if svc.active_subnets() != first:
            rotated = True
            break
    assert rotated, "random subnet never rotated across periods"


def test_committee_subscriptions_expire():
    md = MetadataController()
    svc = AttnetsService(MINIMAL, md)
    svc.add_committee_subscription(7, until_slot=20)
    assert svc.should_process(7)
    assert md.attnets[7] is True
    seq = md.seq_number
    svc.on_slot(21)
    assert not svc.should_process(7)
    assert md.attnets[7] is False
    assert md.seq_number > seq


def test_syncnets_and_metadata_served_over_reqresp():
    async def main():
        from lodestar_tpu.chain.bls_pool import BlsBatchPool
        from lodestar_tpu.chain.handlers import GossipHandlers
        from lodestar_tpu.config.chain_config import ChainConfig
        from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier
        from lodestar_tpu.network import Network
        from lodestar_tpu.node.dev_chain import DevChain

        cfg = ChainConfig(
            PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
            MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
            ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
        )
        pool_a = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        pool_b = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        a = DevChain(MINIMAL, cfg, 16, pool_a)
        b = DevChain(MINIMAL, cfg, 16, pool_b)
        net_a = Network(MINIMAL, a.chain, GossipHandlers(a.chain))
        net_b = Network(MINIMAL, b.chain, GossipHandlers(b.chain))
        # A advertises two attnets before any connection
        net_a.attnets.add_committee_subscription(3, until_slot=100)
        net_a.attnets.add_committee_subscription(9, until_slot=100)
        port = await net_a.listen(0)
        peer = await net_b.connect("127.0.0.1", port)
        md = await peer.reqresp.metadata()
        assert md.seq_number == net_a.metadata.seq_number
        assert list(md.attnets)[3] is True and list(md.attnets)[9] is True
        assert sum(md.attnets) == 2
        await net_b.close()
        await net_a.close()
        pool_a.close()
        pool_b.close()

    asyncio.run(main())


def test_syncnets_service():
    md = MetadataController()
    svc = SyncnetsService(MINIMAL, md)
    svc.add_subscription(2, until_slot=50)
    assert svc.active_subnets() == {2}
    assert md.syncnets == [False, False, True, False]
    svc.on_slot(51)
    assert svc.active_subnets() == set()
    assert md.syncnets == [False] * 4
