"""Differential tests: ops.htc (device hash-to-G2 stages) vs the oracle."""

import random

import numpy as np

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto.bls import curve as C
from lodestar_tpu.crypto.bls import fields as F
from lodestar_tpu.crypto.bls import hash_to_curve as H
from lodestar_tpu.ops import htc
from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops import tower as tw

rng = random.Random(0x2380)


def rand_fq2(n):
    return [F.Fq2(rng.randrange(F.P), rng.randrange(F.P)) for _ in range(n)]


def pack_fq2(vals):
    return jnp.asarray(np.stack([tw.fq2_const(v) for v in vals]))


j_is_square = jax.jit(htc.fq2_is_square)
j_sqrt = jax.jit(htc.fq2_sqrt)
j_sgn0 = jax.jit(htc.fq2_sgn0)
j_sswu = jax.jit(htc.map_to_curve_sswu)
j_map = jax.jit(htc.map_to_curve_g2)
j_hash = jax.jit(htc.hash_to_g2_device)


def unpack_g2_jac(p):
    x, y, z = (np.asarray(a) for a in p)
    out = []
    for i in range(x.shape[0]):
        zf = tw.fq2_to_oracle(z[i])
        if zf.is_zero():
            out.append(C.Point.infinity(C.B2))
        else:
            out.append(C.Point(tw.fq2_to_oracle(x[i]), tw.fq2_to_oracle(y[i]), zf, C.B2))
    return out


class TestFq2SqrtSign:
    def test_is_square(self):
        vals = rand_fq2(6)
        vals += [v.square() for v in vals[:3]]
        vals += [F.Fq2.zero(), F.Fq2.one()]
        out = np.asarray(j_is_square(pack_fq2(vals)))
        assert list(out) == [v.is_square() for v in vals]

    def test_sqrt_of_squares(self):
        vals = [v.square() for v in rand_fq2(6)]
        out = np.asarray(j_sqrt(pack_fq2(vals)))
        for row, v in zip(out, vals):
            got = tw.fq2_to_oracle(row)
            assert got.square() == v

    def test_sgn0(self):
        vals = rand_fq2(6) + [F.Fq2.zero(), F.Fq2(0, 1), F.Fq2(0, 2), F.Fq2(1, 0), F.Fq2(2, 0)]
        out = np.asarray(j_sgn0(pack_fq2(vals)))
        assert [bool(b) for b in out] == [bool(v.sgn0()) for v in vals]


class TestSSWU:
    def test_map_vs_oracle(self):
        us = rand_fq2(4)
        x, y = j_sswu(pack_fq2(us))
        for i, u in enumerate(us):
            ox, oy = H.map_to_curve_sswu(u)
            got_x = tw.fq2_to_oracle(np.asarray(x)[i])
            got_y = tw.fq2_to_oracle(np.asarray(y)[i])
            assert (got_x, got_y) == (ox, oy)

    def test_iso_map_point(self):
        us = rand_fq2(4)
        pts = unpack_g2_jac(j_map(pack_fq2(us)))
        for got, u in zip(pts, us):
            assert got == H.map_to_curve_g2(u)


class TestHashToG2:
    def test_full_vs_oracle(self):
        msgs = [b"", b"abc", b"a longer message for hash to curve", bytes(range(64))]
        u = jnp.asarray(htc.hash_to_field_limbs(msgs))
        pts = unpack_g2_jac(j_hash(u))
        for got, m in zip(pts, msgs):
            want = H.hash_to_g2(m)
            assert got == want
            assert C.g2_subgroup_check(got)
