"""Lane-alignment contract of the fused dispatch graph (round-6 tentpole).

BENCH_r05 rc=124: Mosaic rejected the fused program with "result/input
offset mismatch on non-concat dimension" on a
``vector<256x50xf32> ++ vector<256x2xf32>`` tpu.concatenate — a splice
whose operands sit at a nonzero sublane/lane offset while the
concat-adjacent dims are below the (8, 128) vreg tile.  The fix routes
every such splice through fused_core.aligned_splice (offset-0 zero-pads
+ adds over disjoint supports).

These tests pin the contract ON CPU, without a Mosaic compile:

1. aligned_splice is value-identical to jnp.concatenate.
2. The traced fused call graph (buckets 4 and 128) contains NO
   concatenate that mixes operand extents along the concat dimension
   while every tiled non-concat dim sits below the (8, 128) tile.
3. Shape equivalence: the fused entry points produce exactly the
   XLA-graph kernels' output shapes/dtypes at buckets {4, 128}
   (jax.eval_shape — abstract, no FLOPs).
4. (slow) value equivalence of the fused vs XLA Miller product in
   interpret mode at bucket 4.
5. (TPU only) the fused program COMPILES through Mosaic.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.analysis import jaxpr_audit
from lodestar_tpu.ops import batch_verify as bv
from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops.fused_core import LV, aligned_splice, lconcat
from lodestar_tpu.ops.fused_verify import (
    miller_product_fused,
    verify_signature_sets_fused,
)

rng = np.random.default_rng(29)


# ---------------------------------------------------------------------------
# 1. the splice helper is concatenation, exactly
# ---------------------------------------------------------------------------


class TestAlignedSplice:
    def test_matches_concatenate_float(self):
        for shapes, axis in [
            ([(5, 2, 50), (1, 2, 50)], 0),
            ([(129, 50), (128, 50)], 0),
            ([(3, 50), (4, 50), (1, 50)], 0),
            ([(2, 3, 50), (2, 1, 50)], 1),
        ]:
            arrs = [
                jnp.asarray(rng.integers(0, 256, size=s).astype(np.float32))
                for s in shapes
            ]
            got = aligned_splice(arrs, axis)
            want = jnp.concatenate(arrs, axis)
            assert got.shape == want.shape and (got == want).all()

    def test_matches_concatenate_bool(self):
        a = jnp.asarray(rng.integers(0, 2, size=(7,)).astype(bool))
        b = jnp.asarray(np.array([True]))
        got = aligned_splice([a, b], 0)
        assert (got == jnp.concatenate([a, b])).all()

    def test_lconcat_bound_is_max(self):
        x = LV(jnp.ones((3, 50), jnp.float32), 300)
        y = LV(jnp.ones((1, 50), jnp.float32), 7000)
        out = lconcat([x, y], 0)
        assert out.b == 7000 and out.a.shape == (4, 50)


# ---------------------------------------------------------------------------
# 2 + 3. traced-graph contract at the production buckets
# ---------------------------------------------------------------------------


# The trace machinery (abstract batch args, recursive eqn walk, the
# narrow-mixed-concat predicate) moved to lodestar_tpu.analysis.jaxpr_audit
# where tools/lint.py and tests/test_static_analysis.py drive it too.
# These tests consume the auditor's per-(entry, bucket) ARTIFACTS —
# in-process lru-cached AND persisted under .jax_cache/ keyed by a content
# hash of lodestar_tpu/ops/ — so a trace of the full fused graph (the
# expensive part, ~15-30s) is paid once per ops/ edit, not once per run.

_ENTRY_NAMES = {
    "split": "fused_verify.miller_product_fused",
    "full": "fused_verify.verify_signature_sets_fused",
}


def _mixed_concats(bucket, entry_name):
    art = jaxpr_audit.entry_artifacts(_ENTRY_NAMES[entry_name], bucket)
    return art["mixed_concats"]


# coverage note: split@4, full@4, split@128, full@128 are exactly the
# auditor's AUDIT_BUCKETS matrix, so every combination here rides the
# shared cache
@pytest.mark.parametrize(
    "bucket,entry", [(4, "split"), (4, "full"), (128, "split")]
)
def test_fused_graph_has_no_narrow_mixed_concat(bucket, entry):
    bad = _mixed_concats(bucket, entry)
    assert not bad, f"narrow mixed-width concatenates remain: {bad}"


def _xla_split_avals():
    # the XLA kernel's outputs are batch-independent ((6,2,50) digits +
    # scalar verdict), so ONE trace at bucket 4 is the oracle for every
    # bucket; it comes from the shared auditor cache (the jaxpr audit
    # traces the same entry)
    return jaxpr_audit.entry_out_avals("batch_verify.miller_product_kernel", 4)


@pytest.mark.parametrize("bucket", [4, 128])
def test_fused_shapes_match_xla_kernel(bucket):
    """Interpret-mode shape equivalence vs the XLA-graph kernels: the
    fused twins must be drop-in for TpuBlsVerifier's packing code."""
    got = jaxpr_audit.entry_out_avals(_ENTRY_NAMES["split"], bucket)
    want = _xla_split_avals()
    assert got[0][0] == want[0][0] == (6, 2, fl.NLIMBS)
    assert got[1][0] == want[1][0] == ()
    assert got[1][1] == want[1][1]


def test_fused_full_verdict_shape_matches_xla_kernel():
    # the XLA twin's output is a static scalar bool
    # (batch_verify.verify_signature_sets_kernel docstring) — asserting
    # against the literal avoids a second whole-graph XLA trace
    got_full = jaxpr_audit.entry_out_avals(_ENTRY_NAMES["full"], 4)
    assert got_full == [((), "bool")]


# ---------------------------------------------------------------------------
# 4. value equivalence (slow: full interpret-mode pairing on CPU)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fused_vs_xla_miller_product_value_bucket4():
    from lodestar_tpu.ops.fused_core import f_canon

    args = bv.example_inputs(4)
    f_x, ok_x = jax.jit(bv.miller_product_kernel)(*args)
    f_f, ok_f = miller_product_fused(*[jnp.asarray(a) for a in args], interpret=True)
    assert bool(ok_x) == bool(ok_f) is True
    want = np.asarray(fl.fp_reduce_full(f_x))
    got = np.asarray(f_canon(f_f, True))
    assert (got == want).all()


# ---------------------------------------------------------------------------
# 5. Mosaic compile smoke (the regression BENCH_r05 caught, gated on TPU)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="Mosaic lowering needs a real TPU"
)
def test_fused_program_compiles_on_tpu():
    args = jaxpr_audit._abstract_batch(4)

    def kernel(*a):
        f, ok = miller_product_fused(*a, interpret=False)
        return f.a, ok

    jax.jit(kernel).lower(*args).compile()  # raises on a Mosaic rejection
