"""Lane-alignment contract of the fused dispatch graph (round-6 tentpole).

BENCH_r05 rc=124: Mosaic rejected the fused program with "result/input
offset mismatch on non-concat dimension" on a
``vector<256x50xf32> ++ vector<256x2xf32>`` tpu.concatenate — a splice
whose operands sit at a nonzero sublane/lane offset while the
concat-adjacent dims are below the (8, 128) vreg tile.  The fix routes
every such splice through fused_core.aligned_splice (offset-0 zero-pads
+ adds over disjoint supports).

These tests pin the contract ON CPU, without a Mosaic compile:

1. aligned_splice is value-identical to jnp.concatenate.
2. The traced fused call graph (buckets 4 and 128) contains NO
   concatenate that mixes operand extents along the concat dimension
   while every tiled non-concat dim sits below the (8, 128) tile.
3. Shape equivalence: the fused entry points produce exactly the
   XLA-graph kernels' output shapes/dtypes at buckets {4, 128}
   (jax.eval_shape — abstract, no FLOPs).
4. (slow) value equivalence of the fused vs XLA Miller product in
   interpret mode at bucket 4.
5. (TPU only) the fused program COMPILES through Mosaic.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.ops import batch_verify as bv
from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops.fused_core import LV, aligned_splice, lconcat
from lodestar_tpu.ops.fused_verify import (
    miller_product_fused,
    verify_signature_sets_fused,
)

rng = np.random.default_rng(29)


# ---------------------------------------------------------------------------
# 1. the splice helper is concatenation, exactly
# ---------------------------------------------------------------------------


class TestAlignedSplice:
    def test_matches_concatenate_float(self):
        for shapes, axis in [
            ([(5, 2, 50), (1, 2, 50)], 0),
            ([(129, 50), (128, 50)], 0),
            ([(3, 50), (4, 50), (1, 50)], 0),
            ([(2, 3, 50), (2, 1, 50)], 1),
        ]:
            arrs = [
                jnp.asarray(rng.integers(0, 256, size=s).astype(np.float32))
                for s in shapes
            ]
            got = aligned_splice(arrs, axis)
            want = jnp.concatenate(arrs, axis)
            assert got.shape == want.shape and (got == want).all()

    def test_matches_concatenate_bool(self):
        a = jnp.asarray(rng.integers(0, 2, size=(7,)).astype(bool))
        b = jnp.asarray(np.array([True]))
        got = aligned_splice([a, b], 0)
        assert (got == jnp.concatenate([a, b])).all()

    def test_lconcat_bound_is_max(self):
        x = LV(jnp.ones((3, 50), jnp.float32), 300)
        y = LV(jnp.ones((1, 50), jnp.float32), 7000)
        out = lconcat([x, y], 0)
        assert out.b == 7000 and out.a.shape == (4, 50)


# ---------------------------------------------------------------------------
# 2 + 3. traced-graph contract at the production buckets
# ---------------------------------------------------------------------------


import functools


def _abstract_batch(n):
    S = jax.ShapeDtypeStruct
    return (
        S((n, fl.NLIMBS), jnp.float32),
        S((n, fl.NLIMBS), jnp.float32),
        S((n, 2, fl.NLIMBS), jnp.float32),
        S((n, 2, fl.NLIMBS), jnp.float32),
        S((n, 2, 2, fl.NLIMBS), jnp.float32),
        S((n, 64), jnp.float32),
        S((n,), jnp.bool_),
    )


def _walk_eqns(jaxpr, out):
    for eqn in jaxpr.eqns:
        out.append(eqn)
        for v in eqn.params.values():
            if hasattr(v, "eqns"):
                _walk_eqns(v, out)
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                _walk_eqns(v.jaxpr, out)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if hasattr(item, "eqns"):
                        _walk_eqns(item, out)
                    elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                        _walk_eqns(item.jaxpr, out)


def _split_entry(*a):
    f, ok = miller_product_fused(*a, interpret=True)
    return f.a, ok  # digits + verdict (the static bound is not an output)


_ENTRIES = {
    "split": _split_entry,
    "full": lambda *a: verify_signature_sets_fused(*a, interpret=True),
}


@functools.lru_cache(maxsize=None)
def _traced(bucket, entry_name):
    """One trace per (bucket, entry) shared by the concat and shape tests
    — tracing the full fused graph is the expensive part."""
    return jax.make_jaxpr(_ENTRIES[entry_name])(*_abstract_batch(bucket))


def _narrow_mixed_concats(jaxpr):
    """Concatenate eqns that mix operand extents along the concat dim while
    every tiled non-concat dim (the trailing two, Mosaic's vreg tile) is
    below (8, 128) — the shape class Mosaic cannot retile."""
    eqns = []
    _walk_eqns(jaxpr.jaxpr, eqns)
    bad = []
    for eqn in eqns:
        if eqn.primitive.name != "concatenate":
            continue
        d = eqn.params["dimension"]
        shapes = [v.aval.shape for v in eqn.invars]
        extents = {s[d] for s in shapes}
        if len(extents) == 1:
            continue  # uniform splice, retileable
        rank = len(shapes[0])
        tiled = [(ax, tile) for ax, tile in ((rank - 2, 8), (rank - 1, 128))
                 if 0 <= ax != d]
        if tiled and all(
            s[ax] < tile for s in shapes for ax, tile in tiled
        ):
            bad.append((d, shapes))
    return bad


# coverage note: full@128 is omitted — its batch-dependent subgraph is
# identical to split@128 and its batch-independent tail (final exp +
# is_one, batch shape ()) is covered by full@4; each trace costs ~30s of
# tier-1 wall time, so redundant combinations are skipped deliberately
@pytest.mark.parametrize(
    "bucket,entry", [(4, "split"), (4, "full"), (128, "split")]
)
def test_fused_graph_has_no_narrow_mixed_concat(bucket, entry):
    bad = _narrow_mixed_concats(_traced(bucket, entry))
    assert not bad, f"narrow mixed-width concatenates remain: {bad}"


@functools.lru_cache(maxsize=None)
def _xla_split_avals():
    # the XLA kernel's outputs are batch-independent ((6,2,50) digits +
    # scalar verdict), so ONE trace at bucket 4 is the oracle for every
    # bucket — tracing it per-bucket would only re-spend tier-1 seconds
    return jax.eval_shape(bv.miller_product_kernel, *_abstract_batch(4))


@pytest.mark.parametrize("bucket", [4, 128])
def test_fused_shapes_match_xla_kernel(bucket):
    """Interpret-mode shape equivalence vs the XLA-graph kernels: the
    fused twins must be drop-in for TpuBlsVerifier's packing code."""
    got = _traced(bucket, "split").out_avals
    want = _xla_split_avals()
    assert got[0].shape == want[0].shape == (6, 2, fl.NLIMBS)
    assert got[1].shape == want[1].shape == ()
    assert got[1].dtype == want[1].dtype


def test_fused_full_verdict_shape_matches_xla_kernel():
    # the XLA twin's output is a static scalar bool
    # (batch_verify.verify_signature_sets_kernel docstring) — asserting
    # against the literal avoids a second whole-graph XLA trace
    got_full = _traced(4, "full").out_avals
    assert len(got_full) == 1
    assert got_full[0].shape == ()
    assert got_full[0].dtype == jnp.bool_


# ---------------------------------------------------------------------------
# 4. value equivalence (slow: full interpret-mode pairing on CPU)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fused_vs_xla_miller_product_value_bucket4():
    from lodestar_tpu.ops.fused_core import f_canon

    args = bv.example_inputs(4)
    f_x, ok_x = jax.jit(bv.miller_product_kernel)(*args)
    f_f, ok_f = miller_product_fused(*[jnp.asarray(a) for a in args], interpret=True)
    assert bool(ok_x) == bool(ok_f) is True
    want = np.asarray(fl.fp_reduce_full(f_x))
    got = np.asarray(f_canon(f_f, True))
    assert (got == want).all()


# ---------------------------------------------------------------------------
# 5. Mosaic compile smoke (the regression BENCH_r05 caught, gated on TPU)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="Mosaic lowering needs a real TPU"
)
def test_fused_program_compiles_on_tpu():
    args = _abstract_batch(4)

    def kernel(*a):
        f, ok = miller_product_fused(*a, interpret=False)
        return f.a, ok

    jax.jit(kernel).lower(*args).compile()  # raises on a Mosaic rejection
