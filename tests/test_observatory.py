"""Performance observatory (ISSUE 7): compile ledger classification and
persistence, device telemetry sampling, histogram/percentile agreement
with the firehose, run-trend tripwires, and the tier-1 budget tool.

Budget discipline: everything here is stub-backed and host-side — fake
``memory_stats()`` devices, synthetic monitoring events, the firehose
StubVerifier, fixture JSON series.  Nothing traces or compiles an XLA
program, so the module stays outside the conftest compile whitelist.
"""

import asyncio
import json
import os

import pytest

from lodestar_tpu.metrics import create_metrics
from lodestar_tpu.observatory import (
    bucket_percentile,
    cumulative_counts,
    nearest_rank,
    process_age_s,
)
from lodestar_tpu.observatory import compile_ledger as cl
from lodestar_tpu.observatory import run_ledger
from lodestar_tpu.observatory.device_sampler import DeviceSampler
from lodestar_tpu.observatory.latency import SLO_LATENCY_BUCKETS_S


# ---------------------------------------------------------------------------
# compile ledger
# ---------------------------------------------------------------------------


class TestCompileLedger:
    def test_cold_warm_hit_classification(self):
        """The three-way split from synthetic monitoring events: a bare
        backend compile is cold, one preceded by the persistent-cache
        hit marker is a warm load (the backend event still fires for the
        deserialize — duration alone cannot classify), and an empty
        attribution window is an in-process hit."""
        led = cl.CompileLedger()
        with led.attribute("fused_split", 128, "tpu:0"):
            led.on_jax_event(cl.BACKEND_COMPILE_EVENT, 144.0)
        with led.attribute("fused_split", 128, "tpu:0"):
            led.on_jax_event(cl.CACHE_HIT_EVENT, None)
            led.on_jax_event(cl.BACKEND_COMPILE_EVENT, 25.0)
        with led.attribute("fused_split", 128, "tpu:0"):
            pass  # program already live: no event fires
        kinds = led.summary()["by_entry"]["fused_split"]
        assert kinds["cold"] == {"count": 1, "total_s": 144.0, "max_s": 144.0}
        assert kinds["warm_load"]["count"] == 1
        assert kinds["warm_load"]["total_s"] == 25.0
        assert kinds["hit"]["count"] == 1

    def test_warm_load_without_backend_event_uses_retrieval_time(self):
        led = cl.CompileLedger()
        with led.attribute("xla_split", 4, "cpu:1"):
            led.on_jax_event(cl.CACHE_HIT_EVENT, None)
            led.on_jax_event(cl.CACHE_RETRIEVAL_EVENT, 1.5)
        kinds = led.summary()["by_entry"]["xla_split"]
        assert kinds["warm_load"]["total_s"] == 1.5

    def test_unattributed_events_land_under_other(self):
        led = cl.CompileLedger()
        led.on_jax_event(cl.BACKEND_COMPILE_EVENT, 3.0)
        assert led.summary()["by_entry"]["other"]["cold"]["count"] == 1
        # a stale cache-hit marker is consumed, never reused: two hits
        # then two compiles -> one warm, one cold
        led.on_jax_event(cl.CACHE_HIT_EVENT, None)
        led.on_jax_event(cl.BACKEND_COMPILE_EVENT, 2.0)
        led.on_jax_event(cl.BACKEND_COMPILE_EVENT, 2.0)
        other = led.summary()["by_entry"]["other"]
        assert other["warm_load"]["count"] == 1
        assert other["cold"]["count"] == 2

    def test_roundtrip_and_cross_process_merge(self, tmp_path):
        """Persistence is read-merge-write: a second 'process' writing
        the same key adds counts instead of clobbering (the jaxpr-audit
        artifact pattern, one level lower)."""
        d = str(tmp_path)
        led1 = cl.CompileLedger().configure(cache_dir=d)
        with led1.attribute("fused_full", 128, "tpu:2"):
            led1.on_jax_event(cl.BACKEND_COMPILE_EVENT, 100.0)
        led1.flush()
        led2 = cl.CompileLedger().configure(cache_dir=d)
        with led2.attribute("fused_full", 128, "tpu:2"):
            led2.on_jax_event(cl.BACKEND_COMPILE_EVENT, 90.0)
        led2.flush()
        led3 = cl.CompileLedger().configure(cache_dir=d)
        kinds = led3.summary()["by_entry"]["fused_full"]
        assert kinds["cold"]["count"] == 2
        assert kinds["cold"]["total_s"] == 190.0
        assert kinds["cold"]["max_s"] == 100.0
        # the file itself is schema-tagged JSON with per-key records
        with open(os.path.join(d, cl.LEDGER_FILENAME)) as f:
            data = json.load(f)
        assert data["schema"] == cl.SCHEMA_VERSION
        (key,) = data["records"].keys()
        assert key.startswith("fused_full|b128|tpu:2|jax")

    def test_session_summary_excludes_disk_baseline(self, tmp_path):
        """The cold_start probe's view: what THIS process paid, not the
        historical on-disk ledger — and it must survive the flush()
        record() triggers for cold/warm events."""
        d = str(tmp_path)
        led1 = cl.CompileLedger().configure(cache_dir=d)
        with led1.attribute("fused_full", 128, "tpu:2"):
            led1.on_jax_event(cl.BACKEND_COMPILE_EVENT, 100.0)
        led1.flush()
        led2 = cl.CompileLedger().configure(cache_dir=d)  # loads baseline
        with led2.attribute("xla_split", 4, "cpu:0"):
            led2.on_jax_event(cl.CACHE_HIT_EVENT, None)
            led2.on_jax_event(cl.BACKEND_COMPILE_EVENT, 20.0)
        ss = led2.session_summary()
        assert "fused_full" not in ss  # baseline excluded
        assert ss["xla_split"]["warm_load"]["count"] == 1
        # the merged summary() still carries both
        assert led2.summary()["by_entry"]["fused_full"]["cold"]["count"] == 1

    def test_metrics_observed(self):
        metrics = create_metrics()
        led = cl.CompileLedger(metrics=metrics)
        with led.attribute("fused_split", 128, "tpu:0"):
            led.on_jax_event(cl.BACKEND_COMPILE_EVENT, 144.0)
        with led.attribute("fused_split", 128, "tpu:0"):
            pass
        text = metrics.reg.expose().decode()
        assert (
            'lodestar_bls_compile_seconds_count{entry="fused_split",kind="cold"} 1.0'
            in text
        )
        assert (
            'lodestar_bls_compile_seconds_count{entry="fused_split",kind="hit"} 1.0'
            in text
        )

    def test_journal_sink_feed(self):
        """The PR 5 journal listener forwards its raw monitoring stream
        to registered sinks — the seam the singleton ledger installs
        through (COMPILE_LEDGER.install / configure_persistent_cache)."""
        from lodestar_tpu.forensics import journal as jmod

        led = cl.CompileLedger()
        jmod.add_compile_sink(led.on_jax_event)
        try:
            jmod._notify_sinks(cl.BACKEND_COMPILE_EVENT, 7.0)
            jmod._notify_sinks(cl.CACHE_HIT_EVENT, None)
            assert led.summary()["by_entry"]["other"]["cold"]["count"] == 1
            # a raising sink must not break the feed for others
            def bad(event, duration):
                raise RuntimeError("boom")

            jmod._COMPILE_SINKS.insert(0, bad)
            jmod._notify_sinks(cl.BACKEND_COMPILE_EVENT, 8.0)
            assert led.summary()["by_entry"]["other"]["warm_load"]["count"] == 1
        finally:
            jmod._COMPILE_SINKS[:] = [
                fn for fn in jmod._COMPILE_SINKS
                if fn is not led.on_jax_event and fn.__name__ != "bad"
            ]

    def test_verifier_dispatch_records_inprocess_hits(self):
        """A real TpuBlsVerifier with stub device programs: every warm
        dispatch lands one in-process 'hit' on the ledger (entry named
        for the program key, bucket + executor attributed)."""
        import numpy as np

        from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier

        def hit_count():
            return sum(
                rec["kinds"].get("hit", {}).get("count", 0)
                for k, rec in cl.COMPILE_LEDGER._session.items()
                if k.startswith("xla_split|b4|")
            )

        before = hit_count()
        v = TpuBlsVerifier(buckets=(4,), fused=False)
        n = 4

        def stub_program(*args):
            f = np.zeros((6, 2, 50), dtype=np.float64)
            return f, np.asarray(False)

        v._executors[0].compiled[(n, True, False)] = stub_program
        packed = tuple(np.zeros(s) for s in
                       ((n, 50), (n, 50), (n, 2, 50), (n, 2, 50),
                        (n, 2, 2, 50), (n, 64), (n,)))
        pending = v.dispatch(packed)
        assert pending.result() is False  # ok=False short-circuits on host
        assert hit_count() == before + 1


# ---------------------------------------------------------------------------
# device telemetry sampler
# ---------------------------------------------------------------------------


class FakeDevice:
    def __init__(self, id=0, platform="tpu", stats=None, raise_stats=False):
        self.id = id
        self.platform = platform
        self._stats = stats
        self._raise = raise_stats

    def memory_stats(self):
        if self._raise:
            raise RuntimeError("no stats on this backend")
        return self._stats


class TestDeviceSampler:
    def _inflight(self):
        from lodestar_tpu.forensics.watchdog import InflightTable

        return InflightTable()

    def test_hbm_and_busy_metrics(self):
        from lodestar_tpu.forensics.journal import EventJournal

        metrics = create_metrics()
        journal = EventJournal(64)
        inflight = self._inflight()
        devs = [
            FakeDevice(0, stats={"bytes_in_use": 1 << 30, "bytes_limit": 16 << 30,
                                 "peak_bytes_in_use": 2 << 30,
                                 "ignored_key": "x"}),
            FakeDevice(1, stats=None),  # CPU-style: no stats, no error
        ]
        s = DeviceSampler(interval_s=0.05, devices=devs, metrics=metrics,
                          inflight=inflight, journal=journal, window=4,
                          journal_every=2)
        tok = inflight.register(cid=7, device="tpu:0", bucket=128, sets=100)
        s.tick()  # tpu:0 busy, tpu:1 idle
        inflight.resolve(tok)
        s.tick()  # both idle
        sample = s.tick()
        assert sample["devices"]["tpu:0"]["busy_ratio"] == pytest.approx(1 / 3, abs=1e-3)
        assert sample["devices"]["tpu:1"]["busy_ratio"] == 0.0
        assert sample["devices"]["tpu:0"]["hbm"]["bytes_in_use"] == 1 << 30
        assert "ignored_key" not in sample["devices"]["tpu:0"]["hbm"]
        assert "hbm" not in sample["devices"]["tpu:1"]
        text = metrics.reg.expose().decode()
        assert ('lodestar_bls_device_hbm_bytes{device="tpu:0",'
                'kind="bytes_limit"}') in text
        assert 'lodestar_bls_device_busy_ratio{device="tpu:0"}' in text
        assert 'lodestar_bls_device_busy_ratio{device="tpu:1"} 0.0' in text
        # journal_every=2: 3 ticks -> at least one telemetry.sample event
        kinds = [e["kind"] for e in journal.events()]
        assert "telemetry.sample" in kinds

    def test_memory_stats_failure_is_not_fatal(self):
        inflight = self._inflight()
        s = DeviceSampler(devices=[FakeDevice(0, raise_stats=True)],
                          inflight=inflight)
        sample = s.tick()
        assert "hbm" not in sample["devices"]["tpu:0"]

    def test_default_executor_load_lands_on_first_device(self):
        """The CLI's default deployment: ONE unpinned executor registers
        batches as device='default', but unpinned jax dispatch runs on
        jax.devices()[0] — the busy ratio must land on that device's row
        (not read 0.0 forever while a phantom 'default' row holds it)."""
        inflight = self._inflight()
        tok = inflight.register(device="default")
        s = DeviceSampler(devices=[FakeDevice(0), FakeDevice(1)],
                          inflight=inflight)
        sample = s.tick()
        assert "default" not in sample["devices"]
        assert sample["devices"]["tpu:0"]["busy"] is True
        assert sample["devices"]["tpu:0"]["inflight"] == 1
        assert sample["devices"]["tpu:1"]["busy"] is False
        inflight.resolve(tok)

    def test_inflight_only_device_gets_a_row(self):
        """An executor name the device list doesn't know (stub verifiers
        register device='stub:0') still shows up busy."""
        inflight = self._inflight()
        tok = inflight.register(device="stub:0")
        s = DeviceSampler(devices=[], inflight=inflight)
        sample = s.tick()
        assert sample["devices"]["stub:0"]["busy"] is True
        inflight.resolve(tok)

    def test_overhead_self_accounting(self):
        """The <1% sampler-overhead bound is measured, not promised:
        work_seconds accumulates per tick and overhead_ratio() divides
        by elapsed wall.  A tick over two fake devices costs
        microseconds; the thresholds here are deliberately loose (the
        shared CI box stalls threads for tens of ms under load — the
        REAL bound is published from a bench dev_chain run as
        extras.dev_chain_sampler_overhead_ratio)."""
        import time

        inflight = self._inflight()
        s = DeviceSampler(interval_s=0.05, devices=[FakeDevice(0), FakeDevice(1)],
                          inflight=inflight)
        s.start()
        try:
            time.sleep(0.5)
        finally:
            s.stop()
        assert s.ticks >= 2
        per_tick = s.work_seconds / s.ticks
        assert per_tick < 0.02, f"sampler tick cost {per_tick*1e3:.2f}ms"
        ratio = s.overhead_ratio()
        assert ratio is not None and ratio < 0.5
        snap = s.snapshot()
        assert snap["overhead_ratio"] == ratio
        assert "tpu:0" in snap["devices"]


# ---------------------------------------------------------------------------
# histogram / percentile agreement (tentpole part 3)
# ---------------------------------------------------------------------------


class TestLatencyAgreement:
    def test_nearest_rank_matches_firehose(self):
        from tools.firehose import percentile as firehose_percentile

        import random

        rng = random.Random(1)
        for n in (1, 2, 7, 100, 999):
            vals = [rng.expovariate(20.0) for _ in range(n)]
            for q in (50, 90, 99, 100):
                assert nearest_rank(vals, q) == firehose_percentile(vals, q)

    def test_bucket_percentile_brackets_nearest_rank(self):
        """The /metrics histogram answer and the firehose nearest-rank
        answer agree to one bucket: the raw percentile lies in
        (prev_bound, reported_bound]."""
        import random

        rng = random.Random(7)
        bounds = SLO_LATENCY_BUCKETS_S
        for trial in range(20):
            vals = [rng.expovariate(rng.choice([5.0, 50.0, 500.0]))
                    for _ in range(rng.randrange(1, 400))]
            cc = cumulative_counts(vals, bounds)
            assert cc[-1] == len(vals)
            for q in (50, 90, 99):
                raw = nearest_rank(vals, q)
                est = bucket_percentile(cc, q, bounds)
                assert est is not None
                if raw > bounds[-1]:
                    assert est == bounds[-1]  # clamped to the top edge
                    continue
                assert raw <= est
                idx = bounds.index(est)
                prev = bounds[idx - 1] if idx else 0.0
                assert raw > prev, (raw, est, prev)

    def test_slo_edges_are_exact_bounds(self):
        # the firehose SLO (100ms) and storm deadlines (400ms / 1s) must
        # be exact bucket edges so "met the SLO" is one bucket read
        for edge in (0.1, 0.4, 1.0):
            assert edge in SLO_LATENCY_BUCKETS_S

    def test_empty_and_degenerate(self):
        assert nearest_rank([], 99) is None
        assert bucket_percentile([], 99) is None
        assert bucket_percentile(cumulative_counts([]), 99) is None


# ---------------------------------------------------------------------------
# pool: per-lane histograms, e2e latency, mesh headline (tentpole part 3
# + satellite 2/3)
# ---------------------------------------------------------------------------


class TestPoolHistograms:
    def test_lane_histograms_e2e_and_mesh_gauge(self):
        from lodestar_tpu.chain.bls_pool import BlsBatchPool
        from lodestar_tpu.crypto.bls.verifier import SignatureSetPriority
        from tools.firehose import StubVerifier, _StubSet

        async def main():
            metrics = create_metrics()
            pool = BlsBatchPool(StubVerifier(), max_buffer_wait=0.005,
                                metrics=metrics)
            ok = await asyncio.gather(
                pool.verify_signature_sets(
                    [_StubSet() for _ in range(3)],
                    priority=SignatureSetPriority.BLOCK_PROPOSAL,
                ),
                pool.verify_signature_sets(
                    [_StubSet()], priority=SignatureSetPriority.UNAGGREGATED,
                ),
            )
            assert all(ok)
            pool.close()
            return metrics.reg.expose().decode()

        text = asyncio.run(main())
        # per-lane queue-wait histogram: one JOB per lane observed
        assert ('lodestar_bls_queue_wait_seconds_count'
                '{lane="block_proposal"} 1.0') in text
        assert ('lodestar_bls_queue_wait_seconds_count'
                '{lane="unaggregated"} 1.0') in text
        # e2e verify latency observed per lane at verdict resolution
        assert ('lodestar_bls_e2e_verify_seconds_count'
                '{lane="block_proposal"} 1.0') in text
        # whole-mesh headline gauge set at flush (sets/wall, NOT /chips)
        assert "lodestar_bls_sets_per_sec_mesh" in text
        mesh = [l for l in text.splitlines()
                if l.startswith("lodestar_bls_sets_per_sec_mesh ")]
        assert mesh and float(mesh[0].split()[1]) > 0
        # deprecated aliases still exported for one release
        assert "lodestar_bls_pool_queue_wait_seconds_count 2.0" in text
        assert "lodestar_bls_verifier_stage_seconds" in text

    def test_verifier_stage_duration_histogram(self):
        """TpuBlsVerifier.pack observes the per-call stage histogram
        (host-only work: no device program is traced or compiled)."""
        from lodestar_tpu.crypto.bls.api import interop_secret_key
        from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
        from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet

        metrics = create_metrics()
        v = TpuBlsVerifier(buckets=(4,), metrics=metrics)
        sk = interop_secret_key(0)
        msg = b"\x05" * 32
        sets = [SingleSignatureSet(
            pubkey=sk.to_public_key(), signing_root=msg,
            signature=sk.sign(msg).to_bytes(),
        )]
        assert v.pack(sets) is not None
        text = metrics.reg.expose().decode()
        assert ('lodestar_bls_verifier_stage_duration_seconds_count'
                '{stage="pack"} 1.0') in text


# ---------------------------------------------------------------------------
# run ledger + perf_report tripwires (tentpole part 4)
# ---------------------------------------------------------------------------


def _write_fixture_series(root, per_chip_values):
    """Synthetic BENCH_r*.json files in the committed schema."""
    for i, v in enumerate(per_chip_values, start=1):
        rec = {
            "n": i,
            "rc": 0 if v is not None else 124,
            "parsed": None if v is None else {
                "metric": "bls_sig_sets_per_s_per_chip",
                "value": v,
                "unit": "sig-sets/s",
                "extras": {"dispatch_ms": 580.0},
            },
        }
        with open(os.path.join(root, f"BENCH_r{i:02d}.json"), "w") as f:
            json.dump(rec, f)


class TestPerfReport:
    def test_injected_regression_exits_nonzero(self, tmp_path, capsys):
        """The acceptance fixture: a -15% throughput drop on the last
        run trips the -10% tripwire and perf_report exits 1."""
        from tools.perf_report import main as perf_main

        _write_fixture_series(str(tmp_path), [220.0, 221.0, 219.0, 222.0, 187.0])
        rc = perf_main(["--repo", str(tmp_path),
                        "--out", str(tmp_path / "PERF_TREND.md")])
        assert rc == 1
        md = (tmp_path / "PERF_TREND.md").read_text()
        assert "REGRESSIONS" in md
        assert "bls_sig_sets_per_s_per_chip" in md

    def test_flat_series_flags_plateau_not_regression(self, tmp_path):
        from tools.perf_report import main as perf_main

        _write_fixture_series(str(tmp_path), [None, 222.0, 219.0])
        rc = perf_main(["--repo", str(tmp_path)])
        assert rc == 0  # plateau is a warning, not a gate failure
        report = run_ledger.analyze(str(tmp_path))
        t = report["metrics"]["bls_sig_sets_per_s_per_chip"]
        assert "plateau" in t["flags"]
        assert report["crashed_runs"][0]["rc"] == 124
        assert "r01" in t["gaps"]
        # --fail-on-warn turns the plateau into a gate
        assert perf_main(["--repo", str(tmp_path), "--fail-on-warn"]) == 1

    def test_noise_band_suppresses_jitter(self, tmp_path):
        """A noisy-but-stable series whose last step is within its own
        historical noise band must NOT regress."""
        _write_fixture_series(str(tmp_path), [200.0, 240.0, 205.0, 238.0, 207.0])
        report = run_ledger.analyze(str(tmp_path))
        t = report["metrics"]["bls_sig_sets_per_s_per_chip"]
        assert not any(f.startswith("regression") for f in t["flags"])

    def test_real_repo_series_flags_plateau_and_r05_gap(self):
        """The committed BENCH_r01..r05 series: the ~220 per-chip flat
        line is a plateau and the rc=124 runs are named — the exact
        misses ISSUE 7 cites."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        report = run_ledger.analyze(repo)
        assert report["runs"][:5] == ["r01", "r02", "r03", "r04", "r05"]
        t = report["metrics"]["bls_sig_sets_per_s_per_chip"]
        assert "plateau" in t["flags"]
        crashed = {c["run"]: c["rc"] for c in report["crashed_runs"]}
        assert crashed.get("r05") == 124
        assert not report["regressions"]

    def test_deltas_vs_previous(self, tmp_path):
        _write_fixture_series(str(tmp_path), [220.0, 219.0])
        deltas = run_ledger.deltas_vs_previous(
            str(tmp_path),
            {"bls_sig_sets_per_s_per_chip": 180.0, "dispatch_ms": 580.0,
             "cold_start_warm_s": None},
        )
        d = deltas["bls_sig_sets_per_s_per_chip"]
        assert d["prev"] == 219.0 and d["prev_run"] == "r02"
        assert d["regressed"] is True
        assert deltas["dispatch_ms"]["regressed"] is False
        assert "cold_start_warm_s" not in deltas  # no value, no delta

    def test_committed_perf_trend_is_current(self):
        """PERF_TREND.md is a generated artifact: the committed copy must
        match what tools/perf_report.py renders over the committed
        series (regenerate it when adding a run)."""
        from tools.perf_report import render_markdown

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(repo, "PERF_TREND.md")
        assert os.path.exists(path), "run: python tools/perf_report.py --out PERF_TREND.md"
        committed = open(path).read()

        # compare the stable prefix only: the sidecar sections (compile
        # ledger, tier-1 walls) reflect local .jax_cache state and move
        # with every run by design
        def stable_prefix(md):
            for marker in ("\n## Compile ledger", "\n## Tier-1 wall time"):
                md = md.split(marker)[0]
            return md.strip()

        rendered = render_markdown(run_ledger.analyze(repo))
        assert stable_prefix(committed) == stable_prefix(rendered)
        assert "PLATEAU" in committed
        assert "rc=124" in committed


# ---------------------------------------------------------------------------
# tier-1 budget ledger (satellite 1)
# ---------------------------------------------------------------------------


class TestTier1Budget:
    def _ledger(self, tmp_path, runs):
        cache = tmp_path / ".jax_cache"
        cache.mkdir()
        with open(cache / "tier1_timings.json", "w") as f:
            json.dump({"schema": 1, "runs": runs}, f)
        return str(tmp_path)

    def test_movers_and_margin(self, tmp_path):
        from tools.tier1_budget import analyze, main as budget_main

        repo = self._ledger(tmp_path, [
            {"wall_s": 820.0, "n_tests": 550, "exitstatus": 0,
             "compile_events": 9, "compile_events_s": 300.0,
             "tests": {"tests/test_ops_pairing.py::t": 98.0,
                       "tests/test_small.py::t": 1.0},
             "test_compiles": {"tests/test_ops_pairing.py::t": 3}},
            {"wall_s": 845.0, "n_tests": 551, "exitstatus": 0,
             "compile_events": 9, "compile_events_s": 310.0,
             "tests": {"tests/test_ops_pairing.py::t": 111.0,
                       "tests/test_small.py::t": 1.1},
             "test_compiles": {"tests/test_ops_pairing.py::t": 3}},
        ])
        report = analyze(repo)
        assert report["margin_s"] == 25.0
        assert report["is_full_run"] is True
        top = report["movers"][0]
        assert top["test"] == "tests/test_ops_pairing.py::t"
        assert top["delta_s"] == 13.0  # the PR 6 98s->111s drift, caught
        assert report["wall_delta_s"] == 25.0
        assert report["slowest"][0]["seconds"] == 111.0
        # the <35s margin now gates instead of becoming rc=124
        assert budget_main(["--repo", repo, "--fail-margin", "35"]) == 1
        assert budget_main(["--repo", repo, "--fail-margin", "20"]) == 0

    def test_partial_run_never_gates(self, tmp_path):
        """A `-k` subset (schema-1 legacy ledger) lands in the partial
        ring on read: the margin comes from the latest FULL run even
        when a subset ran after it, so a slow 12-test subset can
        neither trip --fail-margin nor dilute the movers baseline."""
        from tools.tier1_budget import analyze, main as budget_main

        repo = self._ledger(tmp_path, [
            {"wall_s": 800.0, "n_tests": 550, "exitstatus": 0,
             "utc": 100.0, "tests": {}},
            {"wall_s": 860.0, "n_tests": 12, "exitstatus": 0,
             "utc": 200.0, "tests": {}},
        ])
        report = analyze(repo)
        assert report["is_full_run"] is True  # gating entry IS the full run
        assert report["margin_s"] == 70.0  # 870 - 800, never 870 - 860
        assert report["newer_partial"] is True
        assert [r["n_tests"] for r in report["partial_runs"]] == [12]
        assert budget_main(["--repo", repo, "--fail-margin", "35"]) == 0

    def test_partial_ring_cannot_evict_full_baselines(self, tmp_path):
        """The PR 15 bugfix proper: schema-2 rings mean eight -k runs
        after one full run still leave the full run as the movers/margin
        baseline instead of aging it out of a shared last-8 window."""
        from tools.tier1_budget import analyze, load_ledger

        full = {"wall_s": 500.0, "n_tests": 550, "exitstatus": 0,
                "utc": 1.0, "tests": {"tests/test_x.py::t": 9.0}}
        subsets = [
            {"wall_s": 30.0 + i, "n_tests": 10, "exitstatus": 0,
             "utc": 2.0 + i, "tests": {}}
            for i in range(8)
        ]
        repo = self._ledger(tmp_path, [full] + subsets)
        rings = load_ledger(repo)
        assert [r["n_tests"] for r in rings["full"]] == [550]
        assert len(rings["partial"]) == 8
        report = analyze(repo)
        assert report["margin_s"] == 370.0
        assert report["slowest"][0]["test"] == "tests/test_x.py::t"

    def test_schema2_ledger_roundtrip(self, tmp_path):
        """tier1_budget reads the schema-2 layout conftest now writes."""
        from tools.tier1_budget import load_ledger

        cache = tmp_path / ".jax_cache"
        cache.mkdir()
        with open(cache / "tier1_timings.json", "w") as f:
            json.dump({"schema": 2,
                       "runs": [{"wall_s": 500.0, "n_tests": 550,
                                 "exitstatus": 0, "tests": {}}],
                       "partial_runs": [{"wall_s": 12.0, "n_tests": 3,
                                         "exitstatus": 0, "tests": {}}]}, f)
        rings = load_ledger(str(tmp_path))
        assert [r["n_tests"] for r in rings["full"]] == [550]
        assert [r["n_tests"] for r in rings["partial"]] == [3]

    def test_empty_ledger(self, tmp_path):
        from tools.tier1_budget import analyze

        assert analyze(str(tmp_path))["runs"] == []

    def test_conftest_ledger_schema(self):
        """conftest has recorded at least this very session's shape into
        the real ledger path, or none yet — either way the loader copes
        and the writer's schema matches what tier1_budget reads."""
        import tests.conftest as cft

        assert cft._TIER1_LEDGER.endswith("tier1_timings.json")
        # the in-memory collectors exist and carry this session's tests
        assert isinstance(cft._test_durations, dict)

    def test_conftest_writer_splits_rings(self, tmp_path, monkeypatch):
        """_write_tier1_ledger routes a -k subset into partial_runs and a
        full session into runs — the two rings never displace each
        other (satellite: -k runs used to evict full-run baselines)."""
        import tests.conftest as cft

        ledger = tmp_path / ".jax_cache" / "tier1_timings.json"
        monkeypatch.setattr(cft, "_TIER1_LEDGER", str(ledger))
        monkeypatch.setattr(cft, "_compile_log", [])
        monkeypatch.setattr(cft, "_test_compiles", {})
        monkeypatch.setattr(
            cft, "_test_durations", {f"a::t{i}": 1.0 for i in range(3)})
        cft._write_tier1_ledger(0)
        data = json.load(open(ledger))
        assert data["schema"] == 2
        assert data["runs"] == []
        assert [r["n_tests"] for r in data["partial_runs"]] == [3]
        monkeypatch.setattr(
            cft, "_test_durations", {f"a::t{i}": 0.5 for i in range(450)})
        cft._write_tier1_ledger(0)
        data = json.load(open(ledger))
        assert [r["n_tests"] for r in data["runs"]] == [450]
        assert [r["n_tests"] for r in data["partial_runs"]] == [3]


# ---------------------------------------------------------------------------
# REST observatory endpoint + process age
# ---------------------------------------------------------------------------


def test_observatory_endpoint():
    from lodestar_tpu.api.rest import RestApiServer
    from lodestar_tpu.params import MINIMAL

    async def main():
        server = RestApiServer(MINIMAL, chain=None)
        status, payload, ctype = await server._dispatch(
            "GET", "/eth/v1/lodestar/observatory", b""
        )
        assert status == 200
        data = (payload if isinstance(payload, dict) else json.loads(payload))["data"]
        assert "by_entry" in data["compile_ledger"]
        assert data["latency_buckets_s"] == list(SLO_LATENCY_BUCKETS_S)
        assert "device_telemetry" in data  # None until a sampler starts

    asyncio.run(main())


def test_process_age_monotonic():
    import time

    a = process_age_s()
    assert a > 0
    time.sleep(0.02)
    assert process_age_s() > a
