"""Cross-chip sharded pairing (round 11): the mesh tier of
TpuBlsVerifier, the ops/sharded_verify entry family, the jaxpr
auditor's sharded rule set, check_trace's mesh dispatch gate, and the
pool's mesh-wide flush sizing.

Budget discipline (tests/conftest.py compile guard): tier-1 tests here
are stub-program or artifact-riding only —

- verifier/pool/chaos tests inject host stub programs into the mesh
  pseudo-executor (test_multidevice_scheduler discipline: real pack,
  real scheduler, real spans, zero XLA work);
- structural final-exp-once/collective pins read the jaxpr-audit
  artifacts (disk-cached, content-addressed on ops/ — rebuilt by
  ``python tools/lint.py``, abstract traces only, no backend compiles);
- the REAL multi-device executions (GT combine vs the bigint oracle,
  full sharded-entry equivalence) compile small mesh programs (~3-6 s
  each) and are ``@pytest.mark.slow`` — run them standalone with
  ``pytest tests/test_sharded_verify.py -m slow``.
"""

import asyncio
import random
import time

import numpy as np
import pytest

from lodestar_tpu.analysis import jaxpr_audit
from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.chaos import CHAOS
from lodestar_tpu.chaos.plan import FaultPlan
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.crypto.bls.tpu_verifier import (
    _PROGRAM_MEMO,
    _PROGRAM_MEMO_LOCK,
    TpuBlsVerifier,
)
from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet
from lodestar_tpu.forensics.journal import JOURNAL
from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops import tower as tw
from lodestar_tpu.tracing import TRACER

from tools.check_trace import validate_pipeline

SPLIT_ENTRY = "sharded_verify.miller_product_sharded"
FULL_ENTRY = "sharded_verify.verify_signature_sets_sharded"


@pytest.fixture(autouse=True)
def _clean_tracer():
    TRACER.disable()
    TRACER.clear()
    yield
    TRACER.disable()
    TRACER.clear()


def make_sets(n, start=0):
    out = []
    for i in range(start, start + n):
        sk = interop_secret_key(i % 16)
        msg = bytes([i % 256, i // 256 % 256]) * 16
        out.append(
            SingleSignatureSet(
                pubkey=sk.to_public_key(),
                signing_root=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


FQ12_ONE_F32 = np.asarray(tw.FQ12_ONE, dtype=np.float32)


def sharded_stub_verifier(n_devices=4, bucket=8, host_final_exp=False,
                          mesh_program=None, pool_program=None, **kw):
    """Real TpuBlsVerifier (real pack, real routing, real spans) with
    host stubs in BOTH the mesh pseudo-executor and the per-device
    executors, so every tier of the ladder is dispatchable without XLA."""
    import jax

    v = TpuBlsVerifier(
        buckets=(bucket,), devices=jax.devices("cpu")[:n_devices],
        fused=False, host_final_exp=host_final_exp,
        sharded=True, sharded_min_batch=bucket, **kw,
    )
    key = (bucket, host_final_exp, False)
    if mesh_program is None:
        if host_final_exp:
            mesh_program = lambda *a: (FQ12_ONE_F32, np.True_)  # noqa: E731
        else:
            mesh_program = lambda *a: np.True_  # noqa: E731
    v._mesh_ex.compiled[key] = mesh_program
    if pool_program is None:
        pool_program = mesh_program
    for ex in v._executors:
        ex.compiled[key] = pool_program
    return v


# ---------------------------------------------------------------------------
# 1. structural pins over the REAL entry points (artifact-riding)
# ---------------------------------------------------------------------------


class TestShardedEntryStructure:
    def test_sharded_entries_audit_clean(self):
        """Both mesh entries pass the full sharded rule set (collective
        present, final-exp after the combine, no Mosaic-unretileable
        concats in the mapped body, stable cache keys)."""
        if not jaxpr_audit.sharded_audit_available():
            pytest.skip("needs >= 2 devices for the trace-time mesh")
        vs = []
        for name in (SPLIT_ENTRY, FULL_ENTRY):
            vs.extend(
                jaxpr_audit.audit_entry(
                    name, jaxpr_audit.SHARDED_AUDIT_BUCKETS
                )
            )
        assert vs == [], [f"{v.rule}: {v.message}" for v in vs]

    def test_final_exp_runs_once_per_merged_batch(self):
        """The acceptance pin: the split entry contains ZERO final-exp
        scans (the host runs it, once per batch); the full entry
        contains exactly one final exponentiation's worth of pow-x
        scans, every one AFTER the cross-shard combine — never once per
        shard."""
        if not jaxpr_audit.sharded_audit_available():
            pytest.skip("needs >= 2 devices for the trace-time mesh")
        (bucket,) = jaxpr_audit.SHARDED_AUDIT_BUCKETS
        split = jaxpr_audit.entry_artifacts(SPLIT_ENTRY, bucket)["sharded"]
        full = jaxpr_audit.entry_artifacts(FULL_ENTRY, bucket)["sharded"]
        assert split["collectives"], "split entry lost its combine"
        assert split["final_exp_scans"] == 0
        assert full["collectives"], "full entry lost its combine"
        assert full["final_exp_scans"] == jaxpr_audit.FINAL_EXP_POW_SCANS
        assert full["final_exp_scans_before_combine"] == 0

    def test_split_output_contract_matches_single_chip(self):
        """The sharded split entry returns exactly what the single-chip
        split kernel returns — (6, 2, 50) product digits + scalar ok —
        so TpuBlsVerifier's host final-exp path is tier-agnostic."""
        if not jaxpr_audit.sharded_audit_available():
            pytest.skip("needs >= 2 devices for the trace-time mesh")
        (bucket,) = jaxpr_audit.SHARDED_AUDIT_BUCKETS
        sharded_out = jaxpr_audit.entry_out_avals(SPLIT_ENTRY, bucket)
        single_out = jaxpr_audit.entry_out_avals(
            "fused_verify.miller_product_fused", 4
        )
        assert sharded_out == single_out
        assert sharded_out[0][0] == (6, 2, fl.NLIMBS)


class TestShardedRuleFixtures:
    def _mesh(self):
        from lodestar_tpu.ops.sharded_verify import make_mesh

        return make_mesh(n_devices=2)

    def test_no_collective_fixture_fires(self):
        import jax

        from analysis_fixtures import bad_sharded_entry as bad

        jx = jax.make_jaxpr(bad.make_no_collective_entry(self._mesh()))(
            bad.abstract_input(8)
        )
        art = jaxpr_audit.extract_artifacts(jx)
        rules = [
            v.rule for v in jaxpr_audit.check_sharded_rules("fixture", 8, art)
        ]
        assert "jaxpr-sharded-no-collective" in rules

    def test_local_final_exp_fixture_fires(self):
        import jax

        from analysis_fixtures import bad_sharded_entry as bad

        jx = jax.make_jaxpr(bad.make_local_final_exp_entry(self._mesh()))(
            bad.abstract_input(8)
        )
        art = jaxpr_audit.extract_artifacts(jx)
        vs = jaxpr_audit.check_sharded_rules("fixture", 8, art)
        rules = [v.rule for v in vs]
        assert "jaxpr-sharded-local-final-exp" in rules
        assert art["sharded"]["final_exp_scans_before_combine"] == 1

    def test_missing_shard_map_is_a_violation(self):
        """A 'sharded' entry whose trace has no shard_map body at all is
        a single-chip program wearing the mesh's ledger key."""
        art = {"sharded": None}
        rules = [
            v.rule for v in jaxpr_audit.check_sharded_rules("fixture", 8, art)
        ]
        assert rules == ["jaxpr-sharded-no-collective"]


# ---------------------------------------------------------------------------
# 2. verifier routing, identity, and the degrade ladder (stub programs)
# ---------------------------------------------------------------------------


class TestShardedDispatch:
    def test_mesh_routing_span_and_counters(self):
        v = sharded_stub_verifier(n_devices=4, bucket=8)
        TRACER.enable(512)
        p = v.dispatch(v.pack(make_sets(8)))
        assert p.device == "mesh4"
        assert p.result() is True
        assert v.sharded_batches == 1
        span = [s for s in TRACER.spans() if s.name == "bls.dispatch"][0]
        assert span.args["sharded"] is True
        assert span.args["mesh_devices"] == 4
        assert span.args["devices_total"] == 4
        # the mesh slot returned on first result()
        assert v._mesh_ex.inflight == 0
        assert "mesh4" in v.executor_health()

    def test_host_final_exp_once_per_mesh_batch(self):
        """The behavioral half of the final-exp-once pin: a mesh-wide
        split batch costs exactly ONE host final exponentiation (the
        per-device fan-out of the same sets would cost n_devices)."""
        v = sharded_stub_verifier(n_devices=4, bucket=8, host_final_exp=True)
        assert v.dispatch(v.pack(make_sets(8))).result() is True
        assert v.host_final_exps == 1

    def test_small_and_indivisible_batches_ride_the_pool(self):
        v = sharded_stub_verifier(n_devices=4, bucket=8)
        # below sharded_min_batch: per-device placement
        v.buckets = (4, 8)
        for ex in v._executors:
            ex.compiled[(4, False, False)] = lambda *a: np.True_
        p = v.dispatch(v.pack(make_sets(3)))
        assert p.device.startswith("cpu:")
        assert v.sharded_batches == 0
        # a 3-device pool cannot split bucket 8 evenly
        v3 = sharded_stub_verifier(n_devices=3, bucket=8)
        p = v3.dispatch(v3.pack(make_sets(8)))
        assert p.device.startswith("cpu:")
        assert v3.sharded_batches == 0

    def test_mesh_ledger_is_one_entry_not_per_ordinal(self):
        """Satellite pin: a mesh program ledgers as ONE mesh{k}-keyed
        row — never k per-ordinal rows."""
        from lodestar_tpu.observatory.compile_ledger import COMPILE_LEDGER

        v = sharded_stub_verifier(n_devices=4, bucket=8)
        hits_before = (
            COMPILE_LEDGER._session_total.get(
                COMPILE_LEDGER.key("sharded_full", 8, "mesh4"), {}
            ).get("kinds", {}).get("hit", {}).get("count", 0)
        )
        assert v.dispatch(v.pack(make_sets(8))).result() is True
        keys = [k for k in COMPILE_LEDGER._session_total if "sharded" in k]
        assert keys, "mesh dispatch produced no ledger row"
        # ONE mesh{k}-keyed row per program — never per-ordinal rows
        assert all("|mesh4|" in k for k in keys), keys
        assert not any("cpu:" in k for k in keys), keys
        hits_after = (
            COMPILE_LEDGER._session_total.get(
                COMPILE_LEDGER.key("sharded_full", 8, "mesh4"), {}
            ).get("kinds", {}).get("hit", {}).get("count", 0)
        )
        assert hits_after == hits_before + 1

    def test_aot_store_asks_for_the_mesh_key(self):
        """The store tier is consulted under (entry=sharded_*, device=
        mesh{k}) — and a load-only miss is the typed policy refusal."""
        from lodestar_tpu.aot.store import AotStoreMiss

        calls = []

        class FakeStore:
            enabled = True

            def load(self, entry, bucket, device, topology=None):
                calls.append((entry, bucket, device))
                return None

            def save(self, *a, **kw):
                return None

        import jax

        v = TpuBlsVerifier(
            buckets=(8,), devices=jax.devices("cpu")[:4], fused=False,
            host_final_exp=False, sharded=True, sharded_min_batch=8,
            aot_store=FakeStore(), load_only=True,
        )
        with pytest.raises(AotStoreMiss):
            v._mesh_fn(8)
        assert calls == [("sharded_full", 8, "mesh4")]

    def test_enqueue_failure_degrades_to_pool_once(self):
        """A mesh program that cannot even enqueue hops the batch down
        to the per-device tier in the SAME dispatch call: one
        bls.degrade journal event, sticky tier disable, verdict still
        served."""
        def broken(*a):
            raise RuntimeError("mesh lowering exploded")

        v = sharded_stub_verifier(n_devices=4, bucket=8,
                                  mesh_program=broken,
                                  pool_program=lambda *a: np.True_)
        seq0 = JOURNAL.seq
        p = v.dispatch(v.pack(make_sets(8)))
        assert p.device.startswith("cpu:")
        assert p.result() is True
        assert v.sharded is False and v.sharded_fallbacks == 1
        degrades = [
            e for e in JOURNAL.events()
            if e["seq"] >= seq0 and e["kind"] == "bls.degrade"
        ]
        assert len(degrades) == 1
        assert degrades[0]["device"] == "mesh4"
        # tier is sticky-off: the next big batch goes straight to the pool
        assert v.dispatch(v.pack(make_sets(8))).device.startswith("cpu:")
        assert v.sharded_fallbacks == 1

    def test_load_only_warmup_miss_degrades_quietly(self):
        class MissStore:
            enabled = True

            def load(self, *a, **kw):
                return None

            def save(self, *a, **kw):
                return None

        import jax

        v = TpuBlsVerifier(
            buckets=(8,), devices=jax.devices("cpu")[:4], fused=False,
            host_final_exp=False, sharded=True, sharded_min_batch=8,
            aot_store=MissStore(), load_only=True,
        )
        seq0 = JOURNAL.seq
        v.warmup_sharded()
        assert v.sharded is False and v.sharded_fallbacks == 1
        degrades = [
            e for e in JOURNAL.events()
            if e["seq"] >= seq0 and e["kind"] == "bls.degrade"
        ]
        assert len(degrades) == 1 and degrades[0]["device"] == "mesh4"


class TestShardedChaos:
    def test_device_loss_mid_mesh_batch_loses_zero_verdicts(self):
        """Acceptance pin: device.loss during a sharded batch — the
        verdict still resolves (same packed payload requeued onto ONE
        surviving executor), the mesh quarantines, the pool serves."""
        # backoff long enough that it cannot expire mid-test on a loaded
        # box (expiry would legitimately route the probe back to the
        # mesh and break the pool-serves assertion below)
        v = sharded_stub_verifier(n_devices=4, bucket=8,
                                  quarantine_threshold=1,
                                  quarantine_backoff_s=60.0)
        CHAOS.install(
            FaultPlan(seed=11).add(
                "device.loss", match={"device": "mesh4"}, count=1
            )
        )
        try:
            TRACER.enable(512)
            p = v.dispatch(v.pack(make_sets(8)), sets=make_sets(8))
            assert p.device == "mesh4"
            assert p.result() is True  # zero verdicts lost
            assert v.batches_requeued == 1
            assert v.native_fallbacks == 0
            health = v.executor_health()["mesh4"]
            assert health["state"] == "quarantined"
            # quarantined mesh sits out; the pool takes the next batch
            assert not v._sharded_eligible(8)
            p2 = v.dispatch(v.pack(make_sets(8)))
            assert p2.device.startswith("cpu:")
            assert p2.result() is True
            # trace contract: the requeued cid still completes its
            # pipeline with >= 2 dispatch attempts (check_trace enforces)
            spans = [s for s in TRACER.spans() if s.name == "bls.requeue"]
            assert spans and spans[0].args["from_device"] == "mesh4"
        finally:
            CHAOS.disarm()

    def test_backoff_probe_readmits_the_mesh(self):
        v = sharded_stub_verifier(n_devices=4, bucket=8,
                                  quarantine_threshold=1,
                                  quarantine_backoff_s=0.05)
        CHAOS.install(
            FaultPlan(seed=12).add(
                "device.loss", match={"device": "mesh4"}, count=1
            )
        )
        try:
            assert v.dispatch(
                v.pack(make_sets(8)), sets=make_sets(8)
            ).result() is True
        finally:
            CHAOS.disarm()
        assert v.executor_health()["mesh4"]["state"] == "quarantined"
        time.sleep(0.06)  # backoff expires
        # next eligible batch is the ONE probe; its verdict re-admits
        assert v._sharded_eligible(8)
        p = v.dispatch(v.pack(make_sets(8)))
        assert p.device == "mesh4"
        assert p.result() is True
        assert v.executor_health()["mesh4"]["state"] == "healthy"


# ---------------------------------------------------------------------------
# 3. pool sizing + end-to-end trace through check_trace's mesh gate
# ---------------------------------------------------------------------------


class TestPoolMeshWindow:
    def test_flush_merge_cap_grows_when_sharded_active(self):
        """The sharded tier grows the MERGE CAP (storm backlogs form
        mesh-wide batches) but never shrinks the window — sub-threshold
        batches still ride the per-device tier at full pipeline width
        (shrinking the window for those would idle n-1 chips)."""
        v = sharded_stub_verifier(n_devices=4, bucket=8)
        pool = BlsBatchPool(v, flush_threshold=2, pipeline_depth=2,
                            max_buffer_wait=0.005)
        assert pool._flush_window() == (8, 8)  # depth*n_dev, threshold*n_dev
        v.sharded = False
        assert pool._flush_window() == (8, 2)  # depth*n_dev, threshold

    def test_one_mesh_batch_absorbs_the_fanout_and_trace_passes(self):
        """8 concurrent 1-set jobs merge into ONE mesh-spanning batch
        (not 4 per-device placements), and the resulting dump passes
        check_trace's pipeline + mesh rules."""
        v = sharded_stub_verifier(n_devices=4, bucket=8,
                                  host_final_exp=True)

        async def run():
            TRACER.enable(1024)
            pool = BlsBatchPool(v, flush_threshold=8, pipeline_depth=1,
                                max_buffer_wait=0.005)
            jobs = [
                pool.verify_signature_sets([s]) for s in make_sets(8)
            ]
            ok = await asyncio.gather(*jobs)
            pool.close()
            return ok

        ok = asyncio.run(run())
        assert ok == [True] * 8
        disp = [s for s in TRACER.spans() if s.name == "bls.dispatch"]
        assert len(disp) == 1, [s.args for s in disp]
        assert disp[0].args["device"] == "mesh4"
        assert disp[0].args["bucket"] == 8
        assert v.sharded_batches == 1
        # export and hold the dump to the mesh contract
        from lodestar_tpu.tracing import to_chrome_trace

        trace = to_chrome_trace(TRACER)
        errs = validate_pipeline(trace, min_batches=1)
        assert errs == [], errs

    def test_mesh_gate_rejects_lying_spans(self):
        def batch(cid, **disp):
            mk = lambda name, **a: {  # noqa: E731
                "name": name, "ph": "X", "ts": 0, "dur": 5,
                "args": dict(cid=cid, **a),
            }
            return [mk("bls.queue_wait"), mk("bls.pack"),
                    mk("bls.dispatch", **disp), mk("bls.final_exp")]

        # sharded span without mesh_devices
        t = batch(1, device="mesh8", devices_total=8, sharded=True)
        assert any("mesh_devices" in e for e in validate_pipeline(t, 1))
        # sharded span claiming a single-device pool
        t = batch(2, device="mesh8", devices_total=1, sharded=True,
                  mesh_devices=8)
        assert any("devices_total == 1" in e for e in validate_pipeline(t, 1))


# ---------------------------------------------------------------------------
# 4. prewarm --mesh plumbing (no compiles: memo injection)
# ---------------------------------------------------------------------------


class TestMeshWarmup:
    def test_warmup_sharded_serves_from_the_process_memo(self):
        import jax

        v = TpuBlsVerifier(
            buckets=(8,), devices=jax.devices("cpu")[:4], fused=False,
            host_final_exp=False, sharded=True, sharded_min_batch=8,
        )
        key = (8, False, False)
        mk = v._mesh_memo_key(key)
        stub = lambda *a: np.True_  # noqa: E731
        with _PROGRAM_MEMO_LOCK:
            _PROGRAM_MEMO[mk] = stub
        try:
            dt = v.warmup_sharded()
            assert v._mesh_ex.compiled[key] is stub
            assert v.sharded is True  # no degrade
            assert dt < 5.0
        finally:
            with _PROGRAM_MEMO_LOCK:
                _PROGRAM_MEMO.pop(mk, None)

    def test_prewarm_mesh_requires_a_pool(self):
        import tools.prewarm as pw

        with pytest.raises(SystemExit):
            pw.prewarm("/tmp/_nonexistent_store_mesh", (8,), n_devices=1,
                       mesh=True)


# ---------------------------------------------------------------------------
# 5. REAL multi-device execution (slow: ~3-6 s compiles per program)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestCombineOracleEquivalence:
    def _rand_fq12(self, rng):
        from lodestar_tpu.crypto.bls.fields import Fq2, Fq6, Fq12

        c = [rng.randrange(fl.P_INT) for _ in range(12)]
        return Fq12(
            Fq6(Fq2(*c[0:2]), Fq2(*c[2:4]), Fq2(*c[4:6])),
            Fq6(Fq2(*c[6:8]), Fq2(*c[8:10]), Fq2(*c[10:12])),
        )

    @staticmethod
    def _canon(f):
        f = np.asarray(f, dtype=np.float64)
        return [
            fl.limbs_to_int(f[i, j]) % fl.P_INT
            for i in range(6) for j in range(2)
        ]

    @staticmethod
    def _oracle_comps(v):
        out = []
        for six in (v.c0, v.c1):
            for two in (six.c0, six.c1, six.c2):
                out += [two.c0 % fl.P_INT, two.c1 % fl.P_INT]
        return out

    @pytest.mark.parametrize("combine", ["all_gather", "ring"])
    def test_combine_matches_bigint_oracle(self, combine):
        import jax
        from jax.experimental import shard_map as sm
        from jax.sharding import PartitionSpec as P

        from lodestar_tpu.ops import sharded_verify as sv

        rng = random.Random(3)
        vals = [self._rand_fq12(rng) for _ in range(4)]
        expected = vals[0] * vals[1] * vals[2] * vals[3]
        arr = np.stack(
            [tw.fq12_from_oracle(v) for v in vals]
        ).astype(np.float32)
        mesh = sv.make_mesh(n_devices=4)

        def body(x):
            f = x[0]
            if combine == "ring":
                return (sv.fq12_combine_ring(f, 4),)
            return (sv.fq12_combine_all_gather(f),)

        fn = jax.jit(
            sm.shard_map(body, mesh=mesh, in_specs=(P(sv.MESH_AXIS),),
                         out_specs=(P(),), check_rep=False)
        )
        got = self._canon(fn(arr)[0])
        assert got == self._oracle_comps(expected)


@pytest.mark.slow
class TestShardedEntryEquivalence:
    @staticmethod
    def _reduced(f_digits):
        """Final-exponentiated (reduced) pairing value of a device
        Miller product, via the bigint oracle.  The UNREDUCED per-shard
        product differs from the single-chip one — each shard's
        (-g1, S_shard) pair contributes its own Miller garbage — and
        only the final exponentiation collapses them to the same GT
        element (e(-g1,S_a)·e(-g1,S_b) = e(-g1,S_a+S_b) is a statement
        about the REDUCED pairing), so equivalence is asserted there."""
        from lodestar_tpu.crypto.bls.fields import Fq2, Fq6, Fq12
        from lodestar_tpu.crypto.bls.pairing import final_exponentiation

        c = TestCombineOracleEquivalence._canon(f_digits)
        fq12 = Fq12(
            Fq6(Fq2(*c[0:2]), Fq2(*c[2:4]), Fq2(*c[4:6])),
            Fq6(Fq2(*c[6:8]), Fq2(*c[8:10]), Fq2(*c[10:12])),
        )
        return final_exponentiation(fq12)

    def test_sharded_verdict_matches_single_chip(self):
        """The full sharded entry over a 2-device mesh agrees with the
        single-chip kernel — valid sets verify, one corrupted signature
        flips the verdict, and the split entries' Miller products reduce
        to the SAME GT element (the identity, for a valid batch) under
        the final exponentiation."""
        import jax

        from lodestar_tpu.ops import batch_verify as bv
        from lodestar_tpu.ops import sharded_verify as sv

        args = list(bv.example_inputs(4))
        args[6] = np.array([True, True, True, False])  # padding lane
        args = tuple(args)
        mesh = sv.make_mesh(n_devices=2)
        full = jax.jit(sv.verify_signature_sets_sharded(mesh, fused=False))
        assert bool(full(*args)) is True
        single = jax.jit(bv.verify_signature_sets_kernel)
        assert bool(single(*args)) is True
        bad = list(args)
        bad[2] = np.array(bad[2])
        bad[2][0, 0, 0] += 1
        assert bool(full(*tuple(bad))) is False
        split = jax.jit(sv.miller_product_sharded(mesh, fused=False))
        f_sh, ok_sh = split(*args)
        f_1, ok_1 = jax.jit(bv.miller_product_kernel)(*args)
        assert bool(ok_sh) and bool(ok_1)
        r_sh, r_1 = self._reduced(f_sh), self._reduced(f_1)
        assert r_sh.is_one() and r_1.is_one()  # same host verdict: True
