"""End-to-end dev chain: N slots advance with heads tracked and every
signature verified through the batch boundary.

Reference model: beacon-node/test/sim single-node sim (SURVEY §4.4) —
interop genesis, in-process production/import, wait for justification.
"""

import asyncio

import pytest

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.metrics import create_metrics
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL

CFG = ChainConfig(
    PRESET_BASE="minimal",
    SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=32,
)
N_VALIDATORS = 32


class CountingVerifier(PyBlsVerifier):
    def __init__(self):
        super().__init__()
        self.dispatches = 0
        self.sets_seen = 0

    def verify_signature_sets(self, sets):
        self.dispatches += 1
        self.sets_seen += len(sets)
        return super().verify_signature_sets(sets)


def test_dev_chain_advances_and_verifies_through_boundary():
    async def main():
        verifier = CountingVerifier()
        metrics = create_metrics()
        pool = BlsBatchPool(verifier, max_buffer_wait=0.005, metrics=metrics)
        dev = DevChain(MINIMAL, CFG, N_VALIDATORS, pool, metrics=metrics)

        n_slots = MINIMAL.SLOTS_PER_EPOCH + 2  # cross one epoch boundary
        await dev.run(n_slots)

        chain = dev.chain
        head = chain.fork_choice.get_block(chain.head_root)
        assert head.slot == n_slots
        # every block verified through the batched boundary: >= 2 sets/block
        # (proposer+randao), plus attestation aggregates once they flow
        assert verifier.dispatches >= n_slots
        assert verifier.sets_seen >= 2 * n_slots
        # attestations flowed into blocks and fork choice
        assert any(v.next_epoch > 0 for v in chain.fork_choice.votes)
        # head chain is connected back to genesis
        anchor = chain.fork_choice.proto.nodes[0]
        assert chain.fork_choice.is_descendant(anchor.block_root, chain.head_root)
        # metrics observed dispatches
        text = metrics.reg.expose().decode()
        assert "lodestar_bls_pool_dispatches_total" in text
        pool.close()
        return chain

    chain = asyncio.run(main())


def test_dev_chain_two_epochs_justifies():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, N_VALIDATORS, pool)
        finalized_events = []
        from lodestar_tpu.chain.emitter import ChainEvent

        dev.chain.emitter.on(ChainEvent.FINALIZED, lambda cp: finalized_events.append(cp))
        # run 4 epochs + 2 slots: with full participation the chain
        # justifies by the 3rd epoch transition and finalizes on the 4th
        await dev.run(4 * MINIMAL.SLOTS_PER_EPOCH + 2)
        state = dev.chain.head_state()
        assert state.current_justified_checkpoint.epoch >= 2, "no justification after 4 epochs"
        assert state.finalized_checkpoint.epoch >= 1, "no finalization after 4 epochs"
        assert finalized_events, "finalized event not emitted"
        pool.close()

    asyncio.run(main())


def test_dev_chain_rejects_bad_block():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, N_VALIDATORS, pool)
        await dev.run(1)
        # corrupt: re-import a block with a bad proposer signature
        from lodestar_tpu.chain.beacon_chain import BlockError
        from lodestar_tpu.crypto.bls.api import interop_secret_key
        from lodestar_tpu.ssz import Fields
        from lodestar_tpu.state_transition import clone_state, process_slots, compute_epoch_at_slot

        pre = dev.chain.head_state()
        state = clone_state(dev.p, pre)
        ctx = process_slots(dev.p, CFG, state, 2)
        proposer = ctx.get_beacon_proposer(2)
        epoch = compute_epoch_at_slot(dev.p, 2)
        randao = dev._sign_randao(state, proposer, epoch)
        block, _ = dev.chain.produce_block(2, randao)
        bad_signed = Fields(message=block, signature=interop_secret_key(99).sign(b"x" * 32).to_bytes())
        with pytest.raises(BlockError):
            await dev.chain.process_block(bad_signed)
        pool.close()

    asyncio.run(main())
