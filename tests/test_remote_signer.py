"""Remote signer: web3signer-API client + ValidatorStore integration.

Reference: packages/validator/src/util/externalSignerClient.ts and
validatorStore.ts SignerType.Remote — signing roots go over HTTP, key
material never enters the VC, and slashing protection gates before the
request is issued.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.api import PublicKey, Signature, interop_secret_key, verify
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.ssz import Fields
from lodestar_tpu.validator.remote_signer import RemoteSignerClient, RemoteSignerError
from lodestar_tpu.validator.slashing_protection import SlashingError
from lodestar_tpu.validator.store import ValidatorStore


class _MockSigner(BaseHTTPRequestHandler):
    """In-process web3signer double holding interop keys 0..3."""

    keys = {
        interop_secret_key(i).to_public_key().to_bytes(): interop_secret_key(i)
        for i in range(4)
    }

    def log_message(self, *a):  # quiet
        pass

    def _reply(self, code, obj):
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("content-type", "application/json")
        self.send_header("content-length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path == "/upcheck":
            return self._reply(200, {"status": "OK"})
        if self.path == "/api/v1/eth2/publicKeys":
            return self._reply(200, ["0x" + k.hex() for k in self.keys])
        return self._reply(404, {"error": "not found"})

    def do_POST(self):
        if not self.path.startswith("/api/v1/eth2/sign/"):
            return self._reply(404, {"error": "not found"})
        pubkey = bytes.fromhex(self.path.rsplit("/", 1)[1][2:])
        sk = self.keys.get(pubkey)
        if sk is None:
            return self._reply(404, {"error": "unknown key"})
        body = json.loads(self.rfile.read(int(self.headers["content-length"])))
        root = bytes.fromhex(body["signingRoot"][2:])
        return self._reply(200, {"signature": "0x" + sk.sign(root).to_bytes().hex()})


@pytest.fixture(scope="module")
def signer_server():
    srv = HTTPServer(("127.0.0.1", 0), _MockSigner)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv.server_address[1]
    srv.shutdown()


def _remote_store(port: int, indices=range(4)) -> ValidatorStore:
    client = RemoteSignerClient(f"http://127.0.0.1:{port}")
    remote_keys = {
        i: interop_secret_key(i).to_public_key().to_bytes() for i in indices
    }
    return ValidatorStore(
        MINIMAL, ChainConfig(PRESET_BASE="minimal"), {},
        remote_signer=client, remote_keys=remote_keys,
    )


def test_upcheck_and_public_keys(signer_server):
    client = RemoteSignerClient(f"http://127.0.0.1:{signer_server}")
    assert client.up_check()
    keys = client.public_keys()
    assert interop_secret_key(0).to_public_key().to_bytes() in keys


def test_remote_signature_matches_local(signer_server):
    """A remote-signed attestation is byte-identical to local signing —
    the store builds the same signing root either way."""
    remote = _remote_store(signer_server)
    local = ValidatorStore(
        MINIMAL, ChainConfig(PRESET_BASE="minimal"),
        {i: interop_secret_key(i) for i in range(4)},
    )
    data = Fields(
        slot=5, index=0, beacon_block_root=b"\x01" * 32,
        source=Fields(epoch=0, root=b"\x00" * 32),
        target=Fields(epoch=1, root=b"\x02" * 32),
    )
    sig_r = remote.sign_attestation(2, data)
    sig_l = local.sign_attestation(2, data)
    assert sig_r == sig_l
    pk = PublicKey.from_bytes(remote.pubkeys[2])
    # sanity: it really is a valid BLS signature over the signing root
    assert len(sig_r) == 96 and Signature.from_bytes(sig_r)


def test_slashing_protection_gates_before_remote_request(signer_server):
    """A surround/double vote must be refused BEFORE any HTTP leaves."""
    remote = _remote_store(signer_server)
    data1 = Fields(
        slot=5, index=0, beacon_block_root=b"\x01" * 32,
        source=Fields(epoch=0, root=b"\x00" * 32),
        target=Fields(epoch=1, root=b"\x02" * 32),
    )
    remote.sign_attestation(1, data1)
    data2 = Fields(
        slot=5, index=0, beacon_block_root=b"\x03" * 32,
        source=Fields(epoch=0, root=b"\x00" * 32),
        target=Fields(epoch=1, root=b"\x04" * 32),  # same target, diff root
    )
    with pytest.raises(SlashingError):
        remote.sign_attestation(1, data2)


def test_unknown_validator_raises(signer_server):
    remote = _remote_store(signer_server, indices=range(2))
    with pytest.raises(KeyError):
        remote._sign(9, b"\x00" * 32)


def test_unreachable_signer_raises():
    client = RemoteSignerClient("http://127.0.0.1:1")  # nothing listens
    with pytest.raises(RemoteSignerError):
        client.sign(b"\x00" * 48, b"\x00" * 32)
    assert not client.up_check()


def test_validator_registration_signing(signer_server):
    """sign_validator_registration works through the remote path and
    verifies under the builder domain."""
    from lodestar_tpu.execution.builder import ExecutionBuilderMock
    from lodestar_tpu.execution.engine import ExecutionEngineMock

    remote = _remote_store(signer_server)
    reg = remote.sign_validator_registration(3, b"\x0f" * 20, 30_000_000, 99)
    builder = ExecutionBuilderMock(
        MINIMAL, ExecutionEngineMock(MINIMAL), fork_version=b"\x00" * 4
    )
    # the store was built with a default ChainConfig whose
    # GENESIS_FORK_VERSION is 0x00000000 — the builder must use the same
    builder.register_validator([reg])
    assert bytes(reg.message.pubkey) in builder.registrations
