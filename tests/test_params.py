from lodestar_tpu.params import MAINNET, MINIMAL, FAR_FUTURE_EPOCH, DOMAIN_BEACON_ATTESTER
from lodestar_tpu.config import (
    MAINNET_CHAIN_CONFIG,
    MINIMAL_CHAIN_CONFIG,
    ForkName,
    ForkConfig,
    create_beacon_config,
)


def test_mainnet_preset_values():
    assert MAINNET.SLOTS_PER_EPOCH == 32
    assert MAINNET.SHUFFLE_ROUND_COUNT == 90
    assert MAINNET.MAX_VALIDATORS_PER_COMMITTEE == 2048
    assert MAINNET.SYNC_COMMITTEE_SIZE == 512
    assert MAINNET.MAX_EFFECTIVE_BALANCE == 32_000_000_000
    assert MAINNET.SYNC_COMMITTEE_SUBNET_SIZE == 128


def test_minimal_preset_values():
    assert MINIMAL.SLOTS_PER_EPOCH == 8
    assert MINIMAL.SHUFFLE_ROUND_COUNT == 10
    assert MINIMAL.SYNC_COMMITTEE_SIZE == 32
    assert MINIMAL.EPOCHS_PER_SLASHINGS_VECTOR == 64


def test_constants():
    assert FAR_FUTURE_EPOCH == 2**64 - 1
    assert DOMAIN_BEACON_ATTESTER == bytes([1, 0, 0, 0])


def test_fork_schedule_mainnet():
    fc = ForkConfig(MAINNET_CHAIN_CONFIG)
    assert fc.get_fork_info_at_epoch(0).name == ForkName.phase0
    assert fc.get_fork_info_at_epoch(74239).name == ForkName.phase0
    assert fc.get_fork_info_at_epoch(74240).name == ForkName.altair
    assert fc.get_fork_version(74240) == bytes.fromhex("01000000")


def test_fork_digest_roundtrip():
    gvr = b"\x2a" * 32
    bc = create_beacon_config(MINIMAL_CHAIN_CONFIG, gvr)
    digest = bc.fork_name_to_digest(ForkName.altair)
    assert len(digest) == 4
    assert bc.digest_to_fork_name(digest) == ForkName.altair
    # Different versions must give different digests
    assert digest != bc.fork_name_to_digest(ForkName.phase0)


def test_unscheduled_fork_never_selected():
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.params import FAR_FUTURE_EPOCH

    fc = ForkConfig(ChainConfig(PRESET_BASE="mainnet"))  # altair/bellatrix unscheduled
    assert fc.get_fork_info_at_epoch(FAR_FUTURE_EPOCH).name == ForkName.phase0
