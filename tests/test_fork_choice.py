"""Fork-choice unit tests: head tracking, reorg, justified updates, pruning.

Modeled on packages/fork-choice/test/unit (protoArray + forkChoice suites).
"""

import numpy as np
import pytest

from lodestar_tpu.fork_choice import (
    Checkpoint,
    ForkChoice,
    ForkChoiceError,
    ForkChoiceStore,
    ProtoArray,
    ProtoNode,
    VoteTracker,
    compute_deltas,
)


def root(n: int) -> bytes:
    return n.to_bytes(32, "big")


def node(slot, r, parent, j=0, f=0) -> ProtoNode:
    return ProtoNode(
        slot=slot,
        block_root=root(r),
        parent_root=root(parent) if parent is not None else None,
        state_root=root(r),
        target_root=root(r),
        justified_epoch=j,
        finalized_epoch=f,
    )


def make_fc(n_validators=16, balance=32):
    store = ForkChoiceStore(
        current_slot=0,
        justified_checkpoint=Checkpoint(0, root(0)),
        finalized_checkpoint=Checkpoint(0, root(0)),
        justified_balances=np.full(n_validators, balance, dtype=np.int64),
    )
    anchor = node(0, 0, None)
    return ForkChoice(store, anchor)


class TestComputeDeltas:
    def test_vote_moves(self):
        indices = {root(1): 0, root(2): 1}
        votes = [VoteTracker(current_root=root(1), next_root=root(2), next_epoch=1)]
        deltas = compute_deltas(indices, votes, np.array([10]), np.array([10]))
        assert list(deltas) == [-10, 10]
        # vote settled: second call is a no-op
        deltas = compute_deltas(indices, votes, np.array([10]), np.array([10]))
        assert list(deltas) == [0, 0]

    def test_balance_change(self):
        indices = {root(1): 0}
        votes = [VoteTracker(current_root=root(1), next_root=root(1), next_epoch=1)]
        deltas = compute_deltas(indices, votes, np.array([10]), np.array([16]))
        assert list(deltas) == [6]


class TestHeadAndReorg:
    def test_linear_chain_head(self):
        fc = make_fc()
        fc.on_block(1, root(1), root(0), root(1), root(1), Checkpoint(0, root(0)), Checkpoint(0, root(0)))
        fc.on_block(2, root(2), root(1), root(2), root(2), Checkpoint(0, root(0)), Checkpoint(0, root(0)))
        assert fc.update_head() == root(2)

    def test_fork_resolved_by_votes(self):
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp)
        # two children of 1
        fc.on_block(2, root(2), root(1), root(2), root(2), cp, cp)
        fc.on_block(2, root(3), root(1), root(3), root(3), cp, cp)
        # 3 validators vote for block 2, 5 for block 3
        fc.on_attestation([0, 1, 2], root(2), 1)
        fc.on_attestation([3, 4, 5, 6, 7], root(3), 1)
        assert fc.update_head() == root(3)
        # votes move: now 6 validators prefer block 2 -> reorg
        fc.on_attestation([3, 4, 5, 8, 9, 10], root(2), 2)
        assert fc.update_head() == root(2)

    def test_tie_break_higher_root_wins(self):
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(5), root(0), root(5), root(5), cp, cp)
        fc.on_block(1, root(9), root(0), root(9), root(9), cp, cp)
        assert fc.update_head() == root(9)

    def test_unknown_parent_rejected(self):
        fc = make_fc()
        with pytest.raises(ForkChoiceError):
            fc.on_block(1, root(7), root(99), root(7), root(7), Checkpoint(0, root(0)), Checkpoint(0, root(0)))

    def test_proposer_boost(self):
        # boost = 40% of one slot's committee weight = 0.4*total/32; with
        # 128 validators that outweighs a single 32-unit vote
        fc = make_fc(n_validators=128)
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp)
        fc.on_block(2, root(2), root(1), root(2), root(2), cp, cp)
        fc.on_block(2, root(3), root(1), root(3), root(3), cp, cp, is_timely_proposal=True)
        # one vote for 2; boost should still favor 3
        fc.on_attestation([0], root(2), 1)
        assert fc.update_head() == root(3)
        # boost expires next slot; the vote then wins
        fc.update_time(3)
        assert fc.update_head() == root(2)


class TestJustifiedUpdates:
    def test_justified_checkpoint_moves_head_filter(self):
        fc = make_fc()
        cp0 = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp0, cp0)
        fc.on_block(2, root(2), root(1), root(2), root(2), cp0, cp0)
        # block 3 carries a newer justified checkpoint pointing at block 1
        cp1 = Checkpoint(1, root(1))
        fc.on_block(3, root(3), root(2), root(3), root(3), cp1, cp0)
        head = fc.update_head()
        assert head == root(3)
        assert fc.store.justified_checkpoint.epoch == 1


class TestPrune:
    def test_prune_below_threshold_noop(self):
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp)
        assert fc.prune(root(1)) == []

    def test_prune_drops_ancestors(self):
        fc = make_fc()
        fc.proto.prune_threshold = 0
        cp = Checkpoint(0, root(0))
        for i in range(1, 6):
            fc.on_block(i, root(i), root(i - 1), root(i), root(i), cp, cp)
        removed = fc.prune(root(3))
        assert [n.block_root for n in removed] == [root(0), root(1), root(2)]
        assert not fc.has_block(root(1))
        assert fc.has_block(root(4))
        # structure still intact
        fc.store.justified_checkpoint = Checkpoint(0, root(3))
        assert fc.update_head() == root(5)

    def test_get_ancestor(self):
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        for i in range(1, 5):
            fc.on_block(i, root(i), root(i - 1), root(i), root(i), cp, cp)
        assert fc.get_ancestor(root(4), 2) == root(2)
        assert fc.is_descendant(root(1), root(4))
        assert not fc.is_descendant(root(4), root(1))


class TestOptimisticSync:
    def test_invalid_execution_excluded_from_head(self):
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp, execution_status="syncing")
        fc.on_block(2, root(2), root(1), root(2), root(2), cp, cp, execution_status="syncing")
        fc.on_block(2, root(3), root(1), root(3), root(3), cp, cp, execution_status="syncing")
        fc.on_attestation([0, 1, 2], root(2), 1)
        assert fc.update_head() == root(2)
        fc.on_invalid_execution(root(2))
        assert fc.update_head() == root(3)

    def test_invalid_subtree_weight_zeroed_and_reorged(self):
        # votes land deep in a subtree; invalidating the subtree root must
        # strip the whole subtree's weight from ancestors and move the head
        # to the valid sibling branch even though it has fewer votes
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp, execution_status="syncing")
        fc.on_block(2, root(2), root(1), root(2), root(2), cp, cp, execution_status="syncing")
        fc.on_block(3, root(4), root(2), root(4), root(4), cp, cp, execution_status="syncing")
        fc.on_block(2, root(3), root(1), root(3), root(3), cp, cp, execution_status="syncing")
        fc.on_attestation([0, 1, 2, 3, 4], root(4), 1)
        fc.on_attestation([5], root(3), 1)
        assert fc.update_head() == root(4)
        fc.on_invalid_execution(root(2))
        # head reorgs immediately (no fresh votes needed)
        assert fc.update_head() == root(3)
        assert fc.get_block(root(2)).execution_status == "invalid"
        assert fc.get_block(root(4)).execution_status == "invalid"
        assert fc.get_block(root(2)).weight == 0
        assert fc.get_block(root(4)).weight == 0
        # a vote moving OFF the invalidated branch must not double-subtract
        fc.on_attestation([0], root(3), 2)
        assert fc.update_head() == root(3)
        assert fc.get_block(root(3)).weight == 2 * 32

    def test_proposer_boost_uses_preset_slots_per_epoch(self):
        # minimal preset: 8 slots/epoch -> committee weight = total/8.
        # With 16 validators of 32: boost = 0.4 * 512/8 = 25 (floor 25.6
        # -> 25): beats a single 16-unit vote but not a 32-unit one if
        # SLOTS_PER_EPOCH were wrongly 32 (boost would be 6).
        store = ForkChoiceStore(
            current_slot=0,
            justified_checkpoint=Checkpoint(0, root(0)),
            finalized_checkpoint=Checkpoint(0, root(0)),
            justified_balances=np.full(16, 32, dtype=np.int64),
        )
        fc = ForkChoice(store, node(0, 0, None), slots_per_epoch=8)
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp)
        fc.on_block(2, root(2), root(1), root(2), root(2), cp, cp)
        fc.on_block(2, root(3), root(1), root(3), root(3), cp, cp, is_timely_proposal=True)
        fc.on_attestation([0], root(2), 1)  # one 32-unit vote for sibling
        # boost = 40% * (16*32/8) = 25.6 -> floor 25 < 32: vote wins...
        assert fc.update_head() == root(2)
        # ...but with two boosts' worth (wrong //32 would give 8): check
        # the actual applied amount directly
        assert fc._applied_boost is not None
        assert fc._applied_boost[1] == (16 * 32 // 8) * 40 // 100

    def test_valid_execution_marks_ancestors(self):
        fc = make_fc()
        cp = Checkpoint(0, root(0))
        fc.on_block(1, root(1), root(0), root(1), root(1), cp, cp, execution_status="syncing")
        fc.on_block(2, root(2), root(1), root(2), root(2), cp, cp, execution_status="syncing")
        fc.on_valid_execution(root(2))
        assert fc.get_block(root(1)).execution_status == "valid"
        assert fc.get_block(root(2)).execution_status == "valid"
