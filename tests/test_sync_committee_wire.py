"""Sync-committee traffic over the wire + peer-score enforcement.

VERDICT r3 item 6 done-criteria: (a) a two-node test where altair sync
messages/contributions cross the wire into the receiving node's pools,
(b) a misbehaving peer (invalid gossip -> REJECT) is downscored and
disconnected.  Reference: gossip/interface.ts sync-committee topics,
peers/score.ts enforcement.
"""

import asyncio

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.chain.handlers import GossipHandlers
from lodestar_tpu.chain.sync_committee_pools import (
    SYNC_COMMITTEE_SUBNET_COUNT,
    subcommittee_assignment,
)
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.network import Network
from lodestar_tpu.network.peer import (
    MIN_SCORE_BEFORE_BAN,
    PeerAction,
    PeerRpcScoreStore,
    ScoreState,
)
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import DOMAIN_SYNC_COMMITTEE, MINIMAL
from lodestar_tpu.ssz import Fields
from lodestar_tpu.state_transition import compute_epoch_at_slot
from lodestar_tpu.state_transition.domain import get_domain
from lodestar_tpu.types import get_types

# altair from genesis-ish: fork at epoch 1 so sync committees exist early
CFG = ChainConfig(
    PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)
N = 16


async def wait_until(cond, timeout=20.0, interval=0.1):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


def make_pair():
    pool_a = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
    pool_b = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
    a = DevChain(MINIMAL, CFG, N, pool_a)
    b = DevChain(MINIMAL, CFG, N, pool_b)
    return a, b, pool_a, pool_b


def _sign_sync_message(dev, state, slot: int, vi: int):
    """A real SyncCommitteeMessage from interop validator `vi` over the
    head root (validator/services/syncCommittee.ts collapsed)."""
    t = get_types(MINIMAL)
    epoch = compute_epoch_at_slot(MINIMAL, slot)
    domain = get_domain(MINIMAL, state, DOMAIN_SYNC_COMMITTEE, epoch)
    root = t.phase0.SigningData.hash_tree_root(
        Fields(object_root=dev.chain.head_root, domain=domain)
    )
    sig = dev.keys[vi].sign(root)
    return Fields(
        slot=slot,
        beacon_block_root=dev.chain.head_root,
        validator_index=vi,
        signature=sig.to_bytes(),
    )


def test_sync_committee_messages_cross_the_wire():
    async def main():
        a, b, pool_a, pool_b = make_pair()
        # both chains advance into altair together
        for slot in range(1, 10):
            blk = await a.produce_and_import_block(slot)
            b.clock.set_slot(slot)
            await b.chain.process_block(blk)
        assert a.chain.head_root == b.chain.head_root

        net_a = Network(MINIMAL, a.chain, GossipHandlers(a.chain))
        net_b = Network(MINIMAL, b.chain, GossipHandlers(b.chain))
        port = await net_a.listen(0)
        await net_b.connect("127.0.0.1", port)

        slot = 9
        state = b.chain.head_state()
        # pick a validator and its actual subnet
        vi = 0
        subs = subcommittee_assignment(MINIMAL, state, vi)
        assert subs, "interop validator 0 must sit in the sync committee"
        subnet = subs[0]
        msg = _sign_sync_message(b, state, slot, vi)
        # B publishes on the per-subnet topic; A validates into its pool
        n_sent = await net_b.publish_sync_committee_message(msg, subnet=subnet)
        assert n_sent == 1
        # validation runs through the bigint oracle (~100s of ms); poll
        assert await wait_until(
            lambda: net_a.chain.sync_msg_pool.get_contribution(
                slot, a.chain.head_root, subnet
            )
            is not None
        ), "message did not reach A's pool"
        contrib = net_a.chain.sync_msg_pool.get_contribution(slot, a.chain.head_root, subnet)
        assert any(contrib.aggregation_bits)

        await net_b.close()
        await net_a.close()
        pool_a.close()
        pool_b.close()

    asyncio.run(main())


def test_invalid_gossip_downscores_and_disconnects():
    async def main():
        a, b, pool_a, pool_b = make_pair()
        for slot in range(1, 10):
            blk = await a.produce_and_import_block(slot)
            b.clock.set_slot(slot)
            await b.chain.process_block(blk)

        net_a = Network(MINIMAL, a.chain, GossipHandlers(a.chain))
        net_b = Network(MINIMAL, b.chain, GossipHandlers(b.chain))
        port = await net_a.listen(0)
        await net_b.connect("127.0.0.1", port)
        assert len(net_a.peer_manager.peers) == 1

        # B floods A with sync messages carrying garbage signatures from a
        # validator NOT in the right subnet -> REJECT every time; each
        # reject is LOW_TOLERANCE (-10); the peer must be gone well before
        # 10 messages
        state = b.chain.head_state()
        for i in range(8):
            bad = Fields(
                slot=9,
                beacon_block_root=b.chain.head_root,
                validator_index=i,
                signature=bytes([i]) * 96,  # malformed signature
            )
            # vary the payload so the seen-cache doesn't absorb them
            try:
                await net_b.publish_sync_committee_message(bad, subnet=0)
            except Exception:
                break  # connection already dropped by A
            await asyncio.sleep(0.05)
        assert await wait_until(lambda: len(net_a.peer_manager.peers) == 0), (
            "byzantine peer still connected"
        )

        await net_b.close()
        await net_a.close()
        pool_a.close()
        pool_b.close()

    asyncio.run(main())


def test_score_store_decay_and_states():
    store = PeerRpcScoreStore()
    key = "10.0.0.1"
    assert store.state(key) == ScoreState.HEALTHY
    store.apply_action(key, PeerAction.MID_TOLERANCE)
    assert store.state(key) == ScoreState.HEALTHY
    for _ in range(3):
        store.apply_action(key, PeerAction.LOW_TOLERANCE)
    assert store.state(key) == ScoreState.DISCONNECT
    for _ in range(5):
        store.apply_action(key, PeerAction.LOW_TOLERANCE)
    assert store.state(key) == ScoreState.BANNED
    assert store.score(key) >= -100.0
    store.apply_action("other", PeerAction.FATAL)
    assert store.state("other") == ScoreState.BANNED
    # decay pulls scores back toward zero over time
    store._last_update[key] -= 36000  # simulate 10 hours
    assert store.state(key) == ScoreState.HEALTHY
