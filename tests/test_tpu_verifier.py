"""TpuBlsVerifier end-to-end: the same test matrix as PyBlsVerifier
(tests/test_bls_py.py TestVerifierBoundary) driven through the batched
device kernel, plus cross-verifier differential checks.

Runs on the CPU backend (conftest pins JAX_PLATFORMS=cpu); the kernel code
is backend-agnostic.

Split by the PR 15 compile-cost audit (docs/static_analysis.md,
"tier-1 budget discipline"): the real-kernel matrix materializes
xla_split@{4,8} — two ~2.4 MB Miller-product programs whose XLA compile
costs ~900 s on the CPU backend and whose persistent-cache key is not
stable across process contexts, so every fresh tier-1 run risks paying
it cold.  The matrix therefore runs in the nightly ``-m slow`` tier
(where the compile budget is not capped), and tier-1 keeps the entire
host-side surface — pack rejection, bucket selection, chunking, async
lifecycle, metrics, stage accounting — on a verifier whose device
programs are host stubs.  Everything except the XLA executable is real;
the executable itself is pinned nightly here and by
test_dev_chain_tpu.py's slow chain run.
"""

import random

import pytest

from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.crypto.bls.api import (
    SecretKey,
    aggregate_signatures,
    interop_secret_key,
)
from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
from lodestar_tpu.crypto.bls.verifier import (
    AggregatedSignatureSet,
    PyBlsVerifier,
    SingleSignatureSet,
)

rng = random.Random(0xBEEF)
MSG = b"\x42" * 32


@pytest.fixture(scope="module")
def verifier():
    """Real compiled kernels — slow-tier classes only."""
    v = TpuBlsVerifier(buckets=(4, 8))
    yield v
    v.close()


@pytest.fixture(scope="module")
def stub_verifier():
    """Tier-1 host-path verifier: real pack / bucket selection / chunking /
    executor dispatch, device programs replaced by host stubs so no XLA
    program materializes (the compile-cost auditor proves this statically;
    the compile guard enforces it at runtime — this fixture is deliberately
    NOT in COMPILE_WHITELIST)."""
    v = TpuBlsVerifier(buckets=(4, 8), fused=False, host_final_exp=False)
    for ex in v._executors:
        for b in (4, 8):
            ex.compiled[(b, False, False)] = lambda *a: True
    yield v
    v.close()


def make_sets(n, start=0):
    out = []
    for i in range(start, start + n):
        sk = interop_secret_key(i)
        msg = bytes([i % 256]) * 32
        out.append(
            SingleSignatureSet(
                pubkey=sk.to_public_key(),
                signing_root=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


class TestHostPath:
    """Tier-1: the full host surface around the device boundary, zero
    compiles.  Verdict-bearing device semantics (invalid detection, RLC,
    padding masks) live in the slow matrix below."""

    def test_valid_sets_verdict_plumbing(self, stub_verifier):
        assert stub_verifier.verify_signature_sets(make_sets(3))

    def test_empty_batch_raises(self, stub_verifier):
        # reference parity: multithread/index.ts throws on an empty job; a
        # silent False verdict would read as "invalid signature" upstream
        with pytest.raises(ValueError):
            stub_verifier.verify_signature_sets([])
        with pytest.raises(ValueError):
            stub_verifier.verify_signature_sets_async([])

    def test_malformed_signature_bytes_rejected_not_raised(self, stub_verifier):
        sets = make_sets(3)
        sets[0].signature = b"\x00" * 96
        assert not stub_verifier.verify_signature_sets(sets)

    def test_infinity_pubkey_rejected(self, stub_verifier):
        # pack-stage reject: never reaches a device program
        from lodestar_tpu.crypto.bls.api import PublicKey
        from lodestar_tpu.crypto.bls import curve as C

        sets = make_sets(1)
        s = AggregatedSignatureSet(
            pubkeys=[PublicKey(C.Point.infinity(C.B1))],
            signing_root=sets[0].signing_root,
            signature=sets[0].signature,
        )
        assert not stub_verifier.verify_signature_sets([s])

    def test_oversized_batch_chunks(self, stub_verifier):
        # > largest bucket (8): exercises the chunkify path
        before = stub_verifier.dispatches
        assert stub_verifier.verify_signature_sets(make_sets(10))
        assert stub_verifier.dispatches == before + 2

    def test_metrics_counters(self, stub_verifier):
        before = stub_verifier.dispatches
        stub_verifier.verify_signature_sets(make_sets(2))
        assert stub_verifier.dispatches == before + 1
        assert stub_verifier.sets_verified >= 2

    def test_async_returns_pending_then_verdict(self, stub_verifier):
        pending = stub_verifier.verify_signature_sets_async(make_sets(2))
        assert not pending.done_hint()
        assert pending.result() is True
        assert pending.done_hint()
        assert pending.result() is True  # idempotent

    def test_async_malformed_short_circuits_without_dispatch(self, stub_verifier):
        sets = make_sets(1)
        sets[0].signature = b"\xff" * 96
        before = stub_verifier.dispatches
        pending = stub_verifier.verify_signature_sets_async(sets)
        assert pending.done_hint() and pending.result() is False
        assert stub_verifier.dispatches == before  # pack rejected, nothing enqueued

    def test_async_oversized_batch_chunks_back_to_back(self, stub_verifier):
        before = stub_verifier.dispatches
        pending = stub_verifier.verify_signature_sets_async(make_sets(10))
        # both chunks enqueued before any sync
        assert stub_verifier.dispatches == before + 2
        assert pending.result() is True

    def test_stage_seconds_accumulate(self, stub_verifier):
        pack0 = stub_verifier.stage_seconds["pack"]
        assert stub_verifier.verify_signature_sets(make_sets(2))
        assert stub_verifier.stage_seconds["pack"] > pack0


@pytest.mark.slow
class TestTpuVerifierMatrix:
    """Nightly: verdict semantics through REAL compiled kernels
    (xla_split@{4,8} — the single biggest compile in the repo)."""

    def test_valid_sets(self, verifier):
        assert verifier.verify_signature_sets(make_sets(3))

    def test_single_set(self, verifier):
        assert verifier.verify_signature_sets(make_sets(1))

    def test_invalid_set_detected(self, verifier):
        sets = make_sets(3)
        sets[1].signature = interop_secret_key(9).sign(sets[1].signing_root).to_bytes()
        assert not verifier.verify_signature_sets(sets)

    def test_wrong_message_detected(self, verifier):
        sets = make_sets(2)
        sets[0].signing_root = b"\x99" * 32
        assert not verifier.verify_signature_sets(sets)

    def test_aggregated_set(self, verifier):
        sks = [interop_secret_key(i) for i in range(4)]
        agg = aggregate_signatures([s.sign(MSG) for s in sks])
        s = AggregatedSignatureSet(
            pubkeys=[s.to_public_key() for s in sks],
            signing_root=MSG,
            signature=agg.to_bytes(),
        )
        assert verifier.verify_signature_sets([s])

    def test_padding_lanes_do_not_leak(self, verifier):
        # bucket 4 with 2 live sets: padding copies lane 0; a bad lane 0
        # must fail even though its copies are masked
        sets = make_sets(2)
        sets[0].signature = interop_secret_key(7).sign(sets[0].signing_root).to_bytes()
        assert not verifier.verify_signature_sets(sets)

    def test_oversized_batch_chunks(self, verifier):
        # > largest bucket (8): chunkify with a real verdict per chunk
        sets = make_sets(10)
        assert verifier.verify_signature_sets(sets)
        sets[9].signing_root = b"\x01" * 32
        assert not verifier.verify_signature_sets(sets)

    def test_differential_vs_py_verifier(self, verifier):
        py = FastBlsVerifier()
        for trial in range(4):
            sets = make_sets(3, start=trial * 3)
            if trial % 2:
                k = rng.randrange(3)
                sets[k].signature = interop_secret_key(50 + trial).sign(sets[k].signing_root).to_bytes()
            assert verifier.verify_signature_sets(sets) == py.verify_signature_sets(sets)

    def test_stage_seconds_accumulate_through_final_exp(self, verifier):
        # the split path's host final-exp stage only runs on real dispatch
        pack0 = verifier.stage_seconds["pack"]
        fexp0 = verifier.stage_seconds["final_exp"]
        assert verifier.verify_signature_sets(make_sets(2))
        assert verifier.stage_seconds["pack"] > pack0
        assert verifier.stage_seconds["final_exp"] > fexp0


@pytest.mark.slow
class TestAdversarial:
    """Nightly: adversarial inputs whose verdict depends on the device
    program (subgroup check, per-lane RLC)."""

    def test_non_subgroup_signature_rejected(self, verifier):
        # forge bytes for an on-curve, non-subgroup G2 point
        from lodestar_tpu.crypto.bls import curve as C
        from lodestar_tpu.crypto.bls import fields as F

        x = 1
        bad = None
        while bad is None:
            xf = F.Fq2(x, 1)
            y2 = xf.square() * xf + C.B2
            y = y2.sqrt()
            if y is not None:
                cand = C.Point.from_affine(xf, y, C.B2)
                if not C.g2_subgroup_check(cand):
                    bad = cand
            x += 1
        sets = make_sets(2)
        sets[1].signature = C.g2_to_bytes(bad)
        assert not verifier.verify_signature_sets(sets)

    def test_duplicate_sets_ok(self, verifier):
        # identical sets in one batch (RLC coefficients differ per lane)
        s = make_sets(1)
        assert verifier.verify_signature_sets([s[0], s[0], s[0]])


@pytest.mark.slow
class TestWarmupAot:
    def test_warmup_aot_compiles_bucket(self):
        v = TpuBlsVerifier(buckets=(4,))
        dt = v.warmup()
        assert dt >= 0 and v.stage_seconds["warmup"] >= dt
        # the AOT executable (not a jit wrapper) serves the dispatch
        key = (4, v.host_final_exp, v.fused)
        assert key in v._compiled and not hasattr(v._compiled[key], "lower")
        assert v.verify_signature_sets(make_sets(2))
        v.close()
