"""The invariant lint + jaxpr auditor + lock/race audit, end to end on CPU.

Three contracts pinned here:

1. ZERO violations on the live tree — every rule, every layer (the
   acceptance gate tools/lint.py enforces in CI and bench pre-flight).
2. Each rule FIRES on its known-bad fixture (tests/analysis_fixtures/),
   exactly on the marked lines — a checker that never fires is worse
   than no checker.
3. Mutation tests: re-introducing each historical regression class
   (narrow mixed-width concat in fused_core.lstack, a bare .result()
   inside BlsBatchPool._flush, an unlocked PointCache.put) turns the
   suite red.

Budget: everything is abstract-trace / AST / stub-program work — no
device program is compiled or loaded, so the conftest compile guard
stays quiet (that is itself asserted by this module running OUTSIDE the
guard whitelist).  The jaxpr traces ride the same per-process lru_cache
as tests/test_fused_verify_alignment.py.
"""

import ast
import os

import pytest

from lodestar_tpu.analysis import jaxpr_audit, lock_audit
from lodestar_tpu.analysis.ast_lint import (
    AsyncBlockingSyncChecker,
    AwaitHoldingLockChecker,
    BlsSilentExceptChecker,
    MetricsCoverageChecker,
    TracingWallclockChecker,
    lint_source,
    run_ast_lint,
)
from lodestar_tpu.analysis.report import (
    Violation,
    filter_suppressed,
    format_report,
    suppressed_rules,
)

from analysis_fixtures import fixture_source, violation_lines

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# 1. live tree is clean
# ---------------------------------------------------------------------------


class TestLiveTreeClean:
    def test_ast_lint_zero_violations(self):
        vs = run_ast_lint(REPO)
        assert vs == [], format_report(vs)

    def test_lock_audit_zero_violations(self):
        vs = lock_audit.audit_bls_pipeline()
        assert vs == [], format_report(vs)

    def test_lint_cli_exits_zero(self, capsys):
        """tools/lint.py (the CI/bench driver) reports zero violations on
        the final tree — the full suite including the jaxpr audit, whose
        traces ride the shared cache."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "lodestar_lint_cli", os.path.join(REPO, "tools", "lint.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(["--repo", REPO])
        assert rc == 0, capsys.readouterr().out


# ---------------------------------------------------------------------------
# 2. AST rules: fixtures fire exactly on the marked lines
# ---------------------------------------------------------------------------


class TestAstFixtures:
    def _assert_fires_on_marks(self, src, path, checker, rule):
        vs = [v for v in lint_source(src, path, [checker]) if v.rule == rule]
        assert sorted(v.line for v in vs) == violation_lines(src), (
            f"{rule} fired on {sorted(v.line for v in vs)}, fixture marks "
            f"{violation_lines(src)}"
        )

    def test_async_blocking_sync_fixture(self):
        src = fixture_source("bad_async_blocking.py")
        self._assert_fires_on_marks(
            src, "lodestar_tpu/chain/_fixture.py",
            AsyncBlockingSyncChecker(), "async-blocking-sync",
        )

    def test_tracing_wallclock_fixture(self):
        src = fixture_source("bad_tracing_wallclock.py")
        self._assert_fires_on_marks(
            src, "lodestar_tpu/chain/_fixture.py",
            TracingWallclockChecker(), "tracing-wallclock",
        )

    def test_tracing_wallclock_package_scope(self):
        """Under lodestar_tpu/tracing/ EVERY time.time() fires, including
        the one the TRACER-argument scope allows elsewhere."""
        src = fixture_source("bad_tracing_wallclock.py")
        vs = lint_source(
            src, "lodestar_tpu/tracing/_fixture.py", [TracingWallclockChecker()]
        )
        lines = sorted(v.line for v in vs)
        pkg_only = [
            i for i, line in enumerate(src.splitlines(), 1)
            if "# PKG-VIOLATION" in line
        ]
        assert lines == sorted(violation_lines(src) + pkg_only)

    def test_await_holding_lock_fixture(self):
        src = fixture_source("bad_await_holding_lock.py")
        self._assert_fires_on_marks(
            src, "lodestar_tpu/chain/_fixture.py",
            AwaitHoldingLockChecker(), "await-holding-lock",
        )

    def test_bls_silent_except_fixture(self):
        src = fixture_source("bad_bls_silent_except.py")
        self._assert_fires_on_marks(
            src, "lodestar_tpu/crypto/bls/_fixture.py",
            BlsSilentExceptChecker(), "bls-silent-except",
        )

    def test_bls_silent_except_pool_scope_and_out_of_scope(self):
        """The rule bites chain/bls_pool.py but NOT the rest of the tree
        (other packages have their own error-handling disciplines)."""
        src = (
            "def f(x):\n"
            "    try:\n"
            "        return x()\n"
            "    except Exception:\n"
            "        return None\n"
        )
        checker = BlsSilentExceptChecker()
        in_pool = lint_source(src, "lodestar_tpu/chain/bls_pool.py", [checker])
        assert [v.rule for v in in_pool] == ["bls-silent-except"]
        assert in_pool[0].line == 4  # the except handler's line
        out_of_scope = lint_source(
            src, "lodestar_tpu/chain/beacon_chain.py", [checker]
        )
        assert out_of_scope == []

    def test_metrics_coverage_fixture(self, tmp_path):
        reg_dir = tmp_path / "lodestar_tpu" / "metrics"
        reg_dir.mkdir(parents=True)
        reg = 'g = r.gauge("lodestar_test_orphan_metric", "nobody can see me")\n'
        (reg_dir / "registry.py").write_text(reg)
        checker = MetricsCoverageChecker(str(tmp_path))
        vs = checker.check(
            "lodestar_tpu/metrics/registry.py", ast.parse(reg), reg
        )
        assert [v.rule for v in vs] == ["metrics-coverage"]
        assert "lodestar_test_orphan_metric" in vs[0].message
        # a docs mention clears it
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "observability.md").write_text("lodestar_test_orphan_metric\n")
        assert checker.check(
            "lodestar_tpu/metrics/registry.py", ast.parse(reg), reg
        ) == []

    def test_suppression_syntax(self):
        src = "async def f(p):\n    return p.result()  # lint: disable=async-blocking-sync\n"
        assert lint_source(src, "lodestar_tpu/x.py", [AsyncBlockingSyncChecker()]) == []
        assert suppressed_rules("x = 1  # lint: disable=a,b") == {"a", "b"}
        assert suppressed_rules("x = 1  # lint: disable") == set()
        assert suppressed_rules("x = 1  # lint: disable  # why: dev-only") == set()
        assert suppressed_rules("x = 1") is None
        # malformed (space instead of '=') must NOT silently disable-all
        assert suppressed_rules("x = 1  # lint: disable async-blocking-sync") is None
        # a non-matching rule id does NOT suppress
        kept = filter_suppressed(
            [Violation("other-rule", "f.py", 1, "m")],
            {"f.py": "x  # lint: disable=async-blocking-sync"},
        )
        assert len(kept) == 1


# ---------------------------------------------------------------------------
# 3. jaxpr auditor: live entries clean at two buckets; fixtures fire
# ---------------------------------------------------------------------------


class TestJaxprAuditor:
    def test_all_entries_clean_at_two_buckets(self):
        """Every public fused entry point in lodestar_tpu/ops/, audited at
        buckets {4, 128}, zero violations — abstract traces only (this
        module is NOT on the conftest compile-guard whitelist, so a
        device program materializing here would fail the suite)."""
        vs = jaxpr_audit.audit_all(buckets=jaxpr_audit.AUDIT_BUCKETS)
        assert vs == [], format_report(vs)

    def test_narrow_mixed_concat_fixture(self):
        import jax
        import jax.numpy as jnp

        from analysis_fixtures import bad_jaxpr_programs as bad

        jx = jax.make_jaxpr(bad.stacked_18_lanes)(
            jax.ShapeDtypeStruct((18, 2, 50), jnp.float32)
        )
        bad_concats = jaxpr_audit.narrow_mixed_concats(jaxpr_audit.all_eqns(jx))
        assert bad_concats, "18-lane jnp.stack must produce the BENCH_r05 splice"

    def test_f64_leak_fixture(self):
        import jax
        import jax.numpy as jnp

        from analysis_fixtures import bad_jaxpr_programs as bad

        with jax.experimental.enable_x64():
            jx = jax.make_jaxpr(bad.f64_leak)(
                jax.ShapeDtypeStruct((4, 50), jnp.float32)
            )
        vs = jaxpr_audit._check_wide_dtypes(
            "fixture", 4, jaxpr_audit.extract_artifacts(jx)
        )
        assert any(v.rule == "jaxpr-f64-leak" for v in vs)

    def test_host_callback_fixture(self):
        import jax
        import jax.numpy as jnp

        from analysis_fixtures import bad_jaxpr_programs as bad

        jx = jax.make_jaxpr(bad.host_callback)(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        )
        vs = jaxpr_audit._check_callbacks(
            "fixture", 4, jaxpr_audit.extract_artifacts(jx)
        )
        assert any(v.rule == "jaxpr-host-callback" for v in vs)

    def test_captured_scalar_fixture(self):
        import jax
        import jax.numpy as jnp

        from analysis_fixtures import bad_jaxpr_programs as bad

        f = bad.make_captured_scalar_fn()
        jx = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4,), jnp.float32))
        vs = jaxpr_audit._check_cache_keys(
            "fixture", (4,), {4: jaxpr_audit.extract_artifacts(jx)}
        )
        assert any(v.rule == "jaxpr-unstable-cache-key" for v in vs)

    def test_mxu_precision_fixture_exact_lines(self):
        """jaxpr-mxu-precision fires on every contract-dropping dot in the
        fixture, EXACTLY on the ``# VIOLATION`` lines, and stays quiet on
        the full-contract program."""
        import jax
        import jax.numpy as jnp

        from analysis_fixtures import bad_mxu_precision as fx

        marked = set(violation_lines(fixture_source("bad_mxu_precision.py")))
        fired = set()
        for fn, shapes in fx.BAD_PROGRAMS:
            jx = jax.make_jaxpr(fn)(
                *[jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]
            )
            vs = jaxpr_audit._check_mxu_precision(
                fn.__name__, 4, jaxpr_audit.extract_artifacts(jx)
            )
            assert vs, f"{fn.__name__} must trip jaxpr-mxu-precision"
            for v in vs:
                assert v.rule == "jaxpr-mxu-precision"
                assert v.path.endswith("bad_mxu_precision.py"), v.path
                fired.add(v.line)
        assert fired == marked, (sorted(fired), sorted(marked))
        for fn, shapes in fx.GOOD_PROGRAMS:
            jx = jax.make_jaxpr(fn)(
                *[jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]
            )
            vs = jaxpr_audit._check_mxu_precision(
                fn.__name__, 4, jaxpr_audit.extract_artifacts(jx)
            )
            assert vs == [], format_report(vs)

    def test_mxu_precision_live_limb_paths(self):
        """Every LODESTAR_TPU_LIMB_MUL mode traces to a graph whose dots
        (if any) all carry the full precision contract — proven on fresh
        tiny traces, not the artifact cache."""
        import jax
        import jax.numpy as jnp

        from lodestar_tpu.ops import limbs as fl

        # the census dedupes call sites: every dot in the mxu/mxu9 graphs
        # routes through the single limbs._dot_f32 source line
        for mode, expect_dots in (("ladder", 0), ("mxu", 1), ("mxu9", 1)):
            jx = jax.make_jaxpr(
                lambda a, b, m=mode: fl.fp_mul(a, b, mode=m)
            )(
                jax.ShapeDtypeStruct((4, fl.NLIMBS), jnp.float32),
                jax.ShapeDtypeStruct((4, fl.NLIMBS), jnp.float32),
            )
            art = jaxpr_audit.extract_artifacts(jx)
            vs = jaxpr_audit._check_mxu_precision(f"fp_mul@{mode}", 4, art)
            assert vs == [], format_report(vs)
            assert len(art["dot_generals"]) == expect_dots, (
                mode, art["dot_generals"],
            )


# ---------------------------------------------------------------------------
# 4. mutation tests: each historical regression class turns the suite red
# ---------------------------------------------------------------------------


class TestMutations:
    def test_lstack_narrow_concat_mutation(self, monkeypatch):
        """Reverting lstack's >16-lane aligned-splice routing to plain
        jnp.stack re-creates the BENCH_r05 splice and the auditor sees it;
        the live lstack on the same 18 lanes stays clean."""
        import jax
        import jax.numpy as jnp

        from lodestar_tpu.ops import fused_core

        def trace_lstack():
            def prog(x):
                lvs = [fused_core.lv(x[i]) for i in range(18)]
                return fused_core.lstack(lvs, 0).a

            jx = jax.make_jaxpr(prog)(
                jax.ShapeDtypeStruct((18, 2, 50), jnp.float32)
            )
            return jaxpr_audit.narrow_mixed_concats(jaxpr_audit.all_eqns(jx))

        assert trace_lstack() == [], "live lstack must route >16 lanes safely"

        def stack_always(vals, axis):
            return fused_core.LV(
                jnp.stack([v.a for v in vals], axis=axis),
                max(v.b for v in vals),
            )

        monkeypatch.setattr(fused_core, "lstack", stack_always)
        assert trace_lstack(), "mutated lstack must trip the concat rule"

    def test_mxu_precision_drop_mutation(self, monkeypatch):
        """Stripping the precision attribute from limbs._dot_f32 (the
        pre-contract dot shape) trips jaxpr-mxu-precision on a fresh
        fp_mul trace; the live helper is clean on the same trace."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from lodestar_tpu.ops import limbs as fl

        def trace_mxu_mul():
            # trace the un-jitted multiply core: the jit wrapper's trace
            # cache would replay the pre-mutation graph regardless of the
            # patched helper, and clearing global jax caches would force
            # recompiles across the rest of the suite
            jx = jax.make_jaxpr(
                lambda a, b: fl._finalize(fl._mul_digits_mxu(a, b), 22)
            )(
                jax.ShapeDtypeStruct((4, fl.NLIMBS), jnp.float32),
                jax.ShapeDtypeStruct((4, fl.NLIMBS), jnp.float32),
            )
            return jaxpr_audit._check_mxu_precision(
                "fp_mul@mxu", 4, jaxpr_audit.extract_artifacts(jx)
            )

        assert trace_mxu_mul() == [], "live _dot_f32 must carry the contract"

        def naked_dot(x, w):
            return lax.dot_general(
                x, jnp.asarray(w), (((x.ndim - 1,), (0,)), ((), ()))
            )

        monkeypatch.setattr(fl, "_dot_f32", naked_dot)
        assert trace_mxu_mul(), "contract-less dot must trip the rule"

    def test_limb_interval_vacuous_dot_mutation(self, monkeypatch):
        """A vacuous proof on the MXU path turns the suite red: making the
        analyzer's const-aware dot rule return TOP drops fp_mul@mxu
        coverage below the pinned 1.0 (the anti-vacuity gate in
        tests/test_compile_cost.py) — the proof is load-bearing, not
        incidentally green."""
        from lodestar_tpu.analysis import limb_interval as li

        entry = next(
            e for e in li.limb_entries() if e.name == "fp_mul@mxu"
        )
        rep = li.analyze_callable(entry.fn, entry.in_shapes, entry.in_intervals)
        assert rep.coverage == 1.0 and rep.findings == []

        monkeypatch.setattr(
            li._Analyzer, "_dot_interval", lambda self, eqn, ins: li.TOP
        )
        mutated = li.analyze_callable(
            entry.fn, entry.in_shapes, entry.in_intervals
        )
        assert mutated.coverage < 1.0, (
            "TOP dot bounds must be visible as lost coverage — a vacuous "
            "MXU proof would otherwise pass silently"
        )

    def test_bls_pool_bare_result_mutation(self):
        """Injecting a bare .result() into the live _flush source (the
        pre-PR-1 blocking shape) trips async-blocking-sync; the shipped
        source is clean."""
        path = os.path.join(REPO, "lodestar_tpu", "chain", "bls_pool.py")
        with open(path) as f:
            src = f.read()
        rel = "lodestar_tpu/chain/bls_pool.py"
        assert lint_source(src, rel, [AsyncBlockingSyncChecker()]) == []
        target = "ok = await verdict"
        assert target in src, "mutation anchor moved — update this test"
        mutated = src.replace(target, "ok = verdict.result()")
        vs = lint_source(mutated, rel, [AsyncBlockingSyncChecker()])
        assert [v.rule for v in vs] == ["async-blocking-sync"]

    def test_unlocked_point_cache_put_mutation(self):
        """Stripping the lock from PointCache.put (the PR-3 race surface)
        is caught deterministically by the instrumented audit — on the
        FIRST unguarded mutation, no interleaving luck involved."""

        def strip_put_lock(v):
            def unlocked_put(self, key, value):
                if self.maxsize <= 0:
                    return
                self._data[key] = value
                self._data.move_to_end(key)
                while len(self._data) > self.maxsize:
                    self._data.popitem(last=False)

            type(v.point_cache).put = unlocked_put

        vs = lock_audit.audit_bls_pipeline(verifier_mutator=strip_put_lock)
        assert any(
            v.rule == "lock-unguarded-mutation" and "point_cache._data" in v.path
            for v in vs
        ), format_report(vs)

    def test_unguarded_counter_mutation(self):
        """A stats-counter write outside _stats_lock (the shape dispatch()
        had before this PR) is flagged."""
        def bump_unlocked(v):
            v.dispatches += 1

        vs = lock_audit.audit_bls_pipeline(verifier_mutator=bump_unlocked)
        assert any(
            v.rule == "lock-unguarded-mutation" and ".dispatches" in v.message
            for v in vs
        ), format_report(vs)


# ---------------------------------------------------------------------------
# 5. lock-order inversion detector self-test
# ---------------------------------------------------------------------------


class TestLockOrder:
    def test_inversion_detected(self):
        import threading

        aud = lock_audit.LockAuditor()
        a = lock_audit.AuditLock(aud, "A")
        b = lock_audit.AuditLock(aud, "B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        vs = aud.lock_order_violations()
        assert [v.rule for v in vs] == ["lock-order-inversion"]
        assert "A" in vs[0].message and "B" in vs[0].message

    def test_consistent_order_is_clean(self):
        aud = lock_audit.LockAuditor()
        a = lock_audit.AuditLock(aud, "A")
        b = lock_audit.AuditLock(aud, "B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert aud.lock_order_violations() == []
