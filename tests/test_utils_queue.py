import asyncio

import pytest

from lodestar_tpu.utils import JobItemQueue, QueueError, QueueType


def run(coro):
    return asyncio.run(coro)


def test_fifo_order_and_results():
    async def main():
        order = []

        async def process(x):
            order.append(x)
            return x * 2

        q = JobItemQueue(process, max_length=10, max_concurrency=1)
        results = await asyncio.gather(*(q.push(i) for i in range(5)))
        assert results == [0, 2, 4, 6, 8]
        assert order == [0, 1, 2, 3, 4]

    run(main())


def test_max_length_fifo_rejects_new():
    async def main():
        started = asyncio.Event()
        release = asyncio.Event()

        async def process(x):
            started.set()
            await release.wait()
            return x

        q = JobItemQueue(process, max_length=2, max_concurrency=1)
        t1 = asyncio.create_task(q.push(1))
        await started.wait()
        t2 = asyncio.create_task(q.push(2))
        t3 = asyncio.create_task(q.push(3))
        await asyncio.sleep(0)
        with pytest.raises(QueueError):
            await q.push(4)
        release.set()
        assert await asyncio.gather(t1, t2, t3) == [1, 2, 3]
        assert q.metrics.dropped_jobs == 1

    run(main())


def test_lifo_processes_newest_first():
    async def main():
        order = []
        started = asyncio.Event()
        release = asyncio.Event()

        async def process(x):
            if x == 0:
                started.set()
                await release.wait()
            order.append(x)
            return x

        q = JobItemQueue(process, max_length=10, max_concurrency=1, queue_type=QueueType.LIFO)
        tasks = [asyncio.create_task(q.push(0))]
        await started.wait()
        tasks += [asyncio.create_task(q.push(i)) for i in (1, 2, 3)]
        await asyncio.sleep(0)
        release.set()
        await asyncio.gather(*tasks)
        assert order == [0, 3, 2, 1]

    run(main())


def test_abort_rejects_pending():
    async def main():
        release = asyncio.Event()

        async def process(x):
            await release.wait()
            return x

        q = JobItemQueue(process, max_length=10, max_concurrency=1)
        t1 = asyncio.create_task(q.push(1))
        t2 = asyncio.create_task(q.push(2))
        await asyncio.sleep(0)
        q.abort()
        release.set()
        await t1  # running job completes
        with pytest.raises(QueueError):
            await t2  # pending job aborted

    run(main())


def test_drain_batch():
    async def main():
        async def process(x):
            return x

        q = JobItemQueue(process, max_length=100, max_concurrency=0)  # never auto-runs
        tasks = [asyncio.create_task(q.push(i)) for i in range(5)]
        await asyncio.sleep(0)
        batch = q.drain_batch(3)
        assert [item for item, _ in batch] == [0, 1, 2]
        for item, fut in batch:
            fut.set_result(item + 100)
        assert await asyncio.gather(*tasks[:3]) == [100, 101, 102]
        for t in tasks[3:]:
            t.cancel()

    run(main())
