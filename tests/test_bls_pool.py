"""BlsBatchPool tests: merged dispatches, retry-individually, metrics.

Reference behaviors under test: multithread/index.ts:41-57 buffering,
worker.ts:78-88 per-job retry after merged-batch failure.
"""

import asyncio

import pytest

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier, SingleSignatureSet
from lodestar_tpu.metrics import create_metrics


def make_set(i, valid=True):
    sk = interop_secret_key(i)
    msg = bytes([i % 256]) * 32
    signer = sk if valid else interop_secret_key(i + 100)
    return SingleSignatureSet(
        pubkey=sk.to_public_key(),
        signing_root=msg,
        signature=signer.sign(msg).to_bytes(),
    )


class CountingVerifier(PyBlsVerifier):
    def __init__(self):
        super().__init__()
        self.calls = []

    def verify_signature_sets(self, sets):
        self.calls.append(len(sets))
        return super().verify_signature_sets(sets)


def run(coro):
    return asyncio.run(coro)


class TestPool:
    def test_concurrent_jobs_merge_into_one_dispatch(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.01, metrics=create_metrics())
            jobs = [pool.verify_signature_sets([make_set(i)]) for i in range(4)]
            results = await asyncio.gather(*jobs)
            assert results == [True] * 4
            assert len(v.calls) == 1 and v.calls[0] == 4  # one merged dispatch
            pool.close()

        run(main())

    def test_bad_job_retried_individually(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.01)
            jobs = [
                pool.verify_signature_sets([make_set(0)]),
                pool.verify_signature_sets([make_set(1, valid=False)]),
                pool.verify_signature_sets([make_set(2)]),
            ]
            results = await asyncio.gather(*jobs)
            assert results == [True, False, True]
            assert pool.batch_retries == 1
            # 1 merged + 3 individual retries
            assert v.calls == [3, 1, 1, 1]
            pool.close()

        run(main())

    def test_flush_threshold_triggers_immediately(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=5.0, flush_threshold=3)
            jobs = [pool.verify_signature_sets([make_set(i)]) for i in range(3)]
            results = await asyncio.wait_for(asyncio.gather(*jobs), timeout=2.0)
            assert results == [True] * 3
            pool.close()

        run(main())

    def test_non_batchable_direct(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=5.0)
            ok = await pool.verify_signature_sets([make_set(5)], batchable=False)
            assert ok and v.calls == [1]
            pool.close()

        run(main())

    def test_empty_job_false(self):
        async def main():
            pool = BlsBatchPool(CountingVerifier())
            assert not await pool.verify_signature_sets([])
            pool.close()

        run(main())


class TestUtilsExtras:
    def test_logger_children(self):
        from lodestar_tpu.utils.logger import get_logger

        a = get_logger("chain")
        b = get_logger("network")
        assert a.name.endswith("chain") and b.name.endswith("network")
        a.info("hello from test")

    def test_retry(self):
        from lodestar_tpu.utils.retry import retry

        attempts = []

        async def flaky(attempt):
            attempts.append(attempt)
            if attempt < 3:
                raise ValueError("boom")
            return "ok"

        assert run(retry(flaky, retries=5)) == "ok"
        assert attempts == [1, 2, 3]

    def test_metrics_exposition(self):
        m = create_metrics()
        m.bls_pool_dispatches_total.inc()
        m.head_slot.set(42)
        text = m.reg.expose().decode()
        assert "lodestar_bls_pool_dispatches_total" in text
        assert "lodestar_head_slot 42.0" in text
