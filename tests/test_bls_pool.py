"""BlsBatchPool tests: merged dispatches, retry-individually, metrics.

Reference behaviors under test: multithread/index.ts:41-57 buffering,
worker.ts:78-88 per-job retry after merged-batch failure.
"""

import asyncio

import pytest

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier, SingleSignatureSet
from lodestar_tpu.metrics import create_metrics


def make_set(i, valid=True):
    sk = interop_secret_key(i)
    msg = bytes([i % 256]) * 32
    signer = sk if valid else interop_secret_key(i + 100)
    return SingleSignatureSet(
        pubkey=sk.to_public_key(),
        signing_root=msg,
        signature=signer.sign(msg).to_bytes(),
    )


class CountingVerifier(PyBlsVerifier):
    def __init__(self):
        super().__init__()
        self.calls = []

    def verify_signature_sets(self, sets):
        self.calls.append(len(sets))
        return super().verify_signature_sets(sets)


def run(coro):
    return asyncio.run(coro)


class TestPool:
    def test_concurrent_jobs_merge_into_one_dispatch(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.01, metrics=create_metrics())
            jobs = [pool.verify_signature_sets([make_set(i)]) for i in range(4)]
            results = await asyncio.gather(*jobs)
            assert results == [True] * 4
            assert len(v.calls) == 1 and v.calls[0] == 4  # one merged dispatch
            pool.close()

        run(main())

    def test_bad_job_retried_individually(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.01)
            jobs = [
                pool.verify_signature_sets([make_set(0)]),
                pool.verify_signature_sets([make_set(1, valid=False)]),
                pool.verify_signature_sets([make_set(2)]),
            ]
            results = await asyncio.gather(*jobs)
            assert results == [True, False, True]
            assert pool.batch_retries == 1
            # 1 merged + 3 individual retries
            assert v.calls == [3, 1, 1, 1]
            pool.close()

        run(main())

    def test_flush_threshold_triggers_immediately(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=5.0, flush_threshold=3)
            jobs = [pool.verify_signature_sets([make_set(i)]) for i in range(3)]
            results = await asyncio.wait_for(asyncio.gather(*jobs), timeout=2.0)
            assert results == [True] * 3
            pool.close()

        run(main())

    def test_non_batchable_direct(self):
        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=5.0)
            ok = await pool.verify_signature_sets([make_set(5)], batchable=False)
            assert ok and v.calls == [1]
            pool.close()

        run(main())

    def test_empty_job_raises(self):
        async def main():
            pool = BlsBatchPool(CountingVerifier())
            with pytest.raises(ValueError):
                await pool.verify_signature_sets([])
            pool.close()

        run(main())


class StageVerifier:
    """Stage-split fake with deterministic latencies: pack blocks the
    calling thread, the 'device' computes in wall time after dispatch, and
    result() blocks until the device is done then pays the host final-exp
    cost — the TpuBlsVerifier timing shape without a TPU."""

    PACK_S = 0.05
    DEVICE_S = 0.10
    FINAL_S = 0.05

    def __init__(self, verdict_fn=None):
        self.dispatched = 0
        self.verdict_fn = verdict_fn or (lambda sets: True)

    def verify_signature_sets_async(self, sets):
        import time as _t

        _t.sleep(self.PACK_S)  # host packing
        self.dispatched += 1
        ready_at = _t.monotonic() + self.DEVICE_S  # async device compute
        verdict = self.verdict_fn(sets)

        class _Pending:
            def result(_self):
                rem = ready_at - _t.monotonic()
                if rem > 0:
                    _t.sleep(rem)  # device sync
                _t.sleep(self.FINAL_S)  # host final exponentiation
                return verdict

        return _Pending()

    def verify_signature_sets(self, sets):
        return self.verify_signature_sets_async(sets).result()


class TestPipeline:
    def test_pack_overlaps_dispatch_with_three_batches(self):
        """Acceptance: with >=3 queued batches the pipelined flush beats
        the serial sum and >=2 batches are concurrently in flight."""

        async def main():
            import time as _t

            v = StageVerifier()
            metrics = create_metrics()
            pool = BlsBatchPool(
                v, max_buffer_wait=0.005, pipeline_depth=3, metrics=metrics
            )
            depth_seen = []

            async def watch():
                while True:
                    try:
                        depth_seen.append(
                            metrics.bls_pool_inflight_depth._value.get()
                        )
                    except AttributeError:  # prometheus absent -> noop metric
                        depth_seen.append(pool.inflight_peak)
                    await asyncio.sleep(0.004)

            watcher = asyncio.create_task(watch())
            t0 = _t.monotonic()
            # stagger pushes so the flusher drains three separate batches:
            # each lands while the previous batch is still being packed
            jobs = [asyncio.create_task(pool.verify_signature_sets([make_set(0)]))]
            for i in (1, 2):
                await asyncio.sleep(StageVerifier.PACK_S * 0.9)
                jobs.append(
                    asyncio.create_task(pool.verify_signature_sets([make_set(i)]))
                )
            results = await asyncio.gather(*jobs)
            wall = _t.monotonic() - t0
            watcher.cancel()
            assert results == [True] * 3
            assert v.dispatched == 3
            serial = 3 * (
                StageVerifier.PACK_S + StageVerifier.DEVICE_S + StageVerifier.FINAL_S
            )
            assert wall < serial, f"no overlap: wall {wall:.3f}s vs serial {serial:.3f}s"
            assert pool.inflight_peak >= 2
            assert max(depth_seen, default=0) >= 2, depth_seen
            pool.close()

        run(main())

    def test_coalescing_fewer_dispatches_than_jobs(self):
        """flush-threshold vs max-buffer-wait: concurrent pushes share
        dispatches (dispatches < jobs_submitted)."""

        async def main():
            v = CountingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.02, flush_threshold=64)
            jobs = []
            for wave in range(4):
                jobs += [
                    pool.verify_signature_sets([make_set(8 * wave + i)])
                    for i in range(8)
                ]
                await asyncio.sleep(0.002)
            results = await asyncio.gather(*jobs)
            assert results == [True] * 32
            assert len(v.calls) < 32, v.calls  # merged dispatches
            assert sum(v.calls) == 32  # every set verified exactly once
            pool.close()

        run(main())

    def test_retry_individually_on_pipelined_path(self):
        """A poisoned merged batch on the ASYNC path still resolves every
        innocent job (worker.ts:78-88 semantics through the pipeline)."""

        async def main():
            truth = PyBlsVerifier()
            v = StageVerifier(verdict_fn=truth.verify_signature_sets)
            v.PACK_S = v.DEVICE_S = v.FINAL_S = 0.001
            pool = BlsBatchPool(v, max_buffer_wait=0.01, pipeline_depth=2)
            jobs = [
                pool.verify_signature_sets([make_set(0)]),
                pool.verify_signature_sets([make_set(1, valid=False)]),
                pool.verify_signature_sets([make_set(2)]),
            ]
            results = await asyncio.gather(*jobs)
            assert results == [True, False, True]
            assert pool.batch_retries == 1
            pool.close()

        run(main())


class TestUtilsExtras:
    def test_logger_children(self):
        from lodestar_tpu.utils.logger import get_logger

        a = get_logger("chain")
        b = get_logger("network")
        assert a.name.endswith("chain") and b.name.endswith("network")
        a.info("hello from test")

    def test_retry(self):
        from lodestar_tpu.utils.retry import retry

        attempts = []

        async def flaky(attempt):
            attempts.append(attempt)
            if attempt < 3:
                raise ValueError("boom")
            return "ok"

        assert run(retry(flaky, retries=5)) == "ok"
        assert attempts == [1, 2, 3]

    def test_metrics_exposition(self):
        m = create_metrics()
        m.bls_pool_dispatches_total.inc()
        m.head_slot.set(42)
        text = m.reg.expose().decode()
        assert "lodestar_bls_pool_dispatches_total" in text
        assert "lodestar_head_slot 42.0" in text
