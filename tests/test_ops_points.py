"""Differential tests: ops.points (jacobian kernels) vs the oracle curve.py."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.crypto.bls import curve as C
from lodestar_tpu.crypto.bls import fields as F
from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops import points as pt
from lodestar_tpu.ops import tower as tw

rng = random.Random(0xC0FFEE)


def rand_g1(n):
    return [C.G1_GEN * rng.randrange(1, F.R) for _ in range(n)]


def rand_g2(n):
    return [C.G2_GEN * rng.randrange(1, F.R) for _ in range(n)]


def pack_g1(points):
    """Oracle points -> jacobian limb arrays (affine input, z=1); infinity
    encoded as exact-zero z."""
    xs, ys, zs = [], [], []
    for p in points:
        if p.is_infinity():
            xs.append(fl.ONE)
            ys.append(fl.ONE)
            zs.append(fl.ZERO)
        else:
            ax, ay = p.to_affine()
            xs.append(fl.int_to_limbs(ax.n))
            ys.append(fl.int_to_limbs(ay.n))
            zs.append(fl.ONE)
    return (jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ys)), jnp.asarray(np.stack(zs)))


def pack_g2(points):
    xs, ys, zs = [], [], []
    for p in points:
        if p.is_infinity():
            xs.append(tw.FQ2_ONE)
            ys.append(tw.FQ2_ONE)
            zs.append(tw.FQ2_ZERO)
        else:
            ax, ay = p.to_affine()
            xs.append(tw.fq2_const(ax))
            ys.append(tw.fq2_const(ay))
            zs.append(tw.FQ2_ONE)
    return (jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ys)), jnp.asarray(np.stack(zs)))


def unpack_g1(p):
    """Jacobian limb point -> oracle point (batch)."""
    x, y, z = (np.asarray(a) for a in p)
    out = []
    for i in range(x.shape[0]):
        zi = fl.limbs_to_int(z[i]) % F.P
        if zi == 0:
            out.append(C.Point.infinity(C.B1))
        else:
            out.append(
                C.Point(
                    F.Fq(fl.limbs_to_int(x[i])),
                    F.Fq(fl.limbs_to_int(y[i])),
                    F.Fq(zi),
                    C.B1,
                )
            )
    return out


def unpack_g2(p):
    x, y, z = (np.asarray(a) for a in p)
    out = []
    for i in range(x.shape[0]):
        zf = tw.fq2_to_oracle(z[i])
        if zf.is_zero():
            out.append(C.Point.infinity(C.B2))
        else:
            out.append(C.Point(tw.fq2_to_oracle(x[i]), tw.fq2_to_oracle(y[i]), zf, C.B2))
    return out


j_dbl_g1 = jax.jit(lambda p: pt.point_double(p, pt.FQ_NS))
j_dbl_g2 = jax.jit(lambda p: pt.point_double(p, pt.FQ2_NS))
j_add_g1 = jax.jit(lambda p, q: pt.point_add_unsafe(p, q, pt.FQ_NS))
j_add_g2 = jax.jit(lambda p, q: pt.point_add_unsafe(p, q, pt.FQ2_NS))
j_addc_g1 = jax.jit(lambda p, q: pt.point_add_complete(p, q, pt.FQ_NS))
j_addc_g2 = jax.jit(lambda p, q: pt.point_add_complete(p, q, pt.FQ2_NS))
j_eq_g1 = jax.jit(lambda p, q: pt.point_eq(p, q, pt.FQ_NS))
j_mulbits_g1 = jax.jit(lambda p, b: pt.point_mul_bits(p, b, pt.FQ_NS))
j_mulbits_g2 = jax.jit(lambda p, b: pt.point_mul_bits(p, b, pt.FQ2_NS))
j_psi = jax.jit(pt.psi)
j_g1_check = jax.jit(pt.g1_subgroup_check)
j_g2_check = jax.jit(pt.g2_subgroup_check)
j_sum_g1 = jax.jit(lambda p: pt.point_sum_tree(p, pt.FQ_NS))
j_affine_g1 = jax.jit(lambda p: pt.point_to_affine(p, pt.FQ_NS))


N = 8


class TestDoubleAdd:
    def test_double_g1(self):
        ps = rand_g1(N) + [C.Point.infinity(C.B1)]
        out = unpack_g1(j_dbl_g1(pack_g1(ps)))
        assert out == [p.double() for p in ps]

    def test_double_g2(self):
        ps = rand_g2(4) + [C.Point.infinity(C.B2)]
        out = unpack_g2(j_dbl_g2(pack_g2(ps)))
        assert out == [p.double() for p in ps]

    def test_add_unsafe_g1(self):
        ps, qs = rand_g1(N), rand_g1(N)
        # include infinity on both sides
        ps.append(C.Point.infinity(C.B1))
        qs.append(rand_g1(1)[0])
        ps.append(rand_g1(1)[0])
        qs.append(C.Point.infinity(C.B1))
        out = unpack_g1(j_add_g1(pack_g1(ps), pack_g1(qs)))
        assert out == [p + q for p, q in zip(ps, qs)]

    def test_add_unsafe_g2(self):
        ps, qs = rand_g2(4), rand_g2(4)
        out = unpack_g2(j_add_g2(pack_g2(ps), pack_g2(qs)))
        assert out == [p + q for p, q in zip(ps, qs)]

    def test_add_complete_edge_cases(self):
        a, b = rand_g1(2)
        inf = C.Point.infinity(C.B1)
        ps = [a, a, a, inf, a, inf]
        qs = [a, -a, b, a, inf, inf]
        out = unpack_g1(j_addc_g1(pack_g1(ps), pack_g1(qs)))
        assert out == [p + q for p, q in zip(ps, qs)]

    def test_add_complete_g2_edges(self):
        a, b = rand_g2(2)
        ps = [a, a, a]
        qs = [a, -a, b]
        out = unpack_g2(j_addc_g2(pack_g2(ps), pack_g2(qs)))
        assert out == [p + q for p, q in zip(ps, qs)]


class TestEqAffine:
    def test_eq(self):
        a, b = rand_g1(2)
        scaled = C.Point(a.x * F.Fq(4), a.y * F.Fq(8), a.z * F.Fq(2), C.B1)  # same affine
        inf = C.Point.infinity(C.B1)
        ps = [a, a, inf, a]
        qs = [scaled, b, inf, inf]
        out = np.asarray(j_eq_g1(pack_g1(ps), pack_g1(qs)))
        assert list(out) == [True, False, True, False]

    def test_to_affine(self):
        ps = rand_g1(4)
        doubled = j_dbl_g1(pack_g1(ps))  # nontrivial z
        xa, ya = j_affine_g1(doubled)
        for i, p in enumerate(ps):
            ax, ay = p.double().to_affine()
            assert fl.limbs_to_int(np.asarray(fl.fp_reduce_full(xa))[i]) == ax.n
            assert fl.limbs_to_int(np.asarray(fl.fp_reduce_full(ya))[i]) == ay.n


class TestScalarMul:
    def test_mul_bits_g1(self):
        ps = rand_g1(N)
        ks = [rng.randrange(0, 1 << 64) for _ in range(N)]
        bits = np.array([[(k >> i) & 1 for i in range(64)] for k in ks], dtype=np.uint32)
        out = unpack_g1(j_mulbits_g1(pack_g1(ps), jnp.asarray(bits)))
        assert out == [p * k for p, k in zip(ps, ks)]

    def test_mul_bits_g2(self):
        ps = rand_g2(4)
        ks = [rng.randrange(0, 1 << 64) for _ in range(4)]
        bits = np.array([[(k >> i) & 1 for i in range(64)] for k in ks], dtype=np.uint32)
        out = unpack_g2(j_mulbits_g2(pack_g2(ps), jnp.asarray(bits)))
        assert out == [p * k for p, k in zip(ps, ks)]

    def test_mul_static(self):
        ps = rand_g1(4)
        for k in (0, 3, F.BLS_X * F.BLS_X - 1):
            f = jax.jit(lambda p, k=k: pt.point_mul_static(p, k, pt.FQ_NS))
            out = unpack_g1(f(pack_g1(ps)))
            assert out == [p * k for p in ps]

    def test_sum_tree(self):
        for n in (1, 2, 3, 7, 8):
            ps = rand_g1(n)
            out = unpack_g1(tuple(a[None] for a in j_sum_g1(pack_g1(ps))))
            acc = C.Point.infinity(C.B1)
            for p in ps:
                acc = acc + p
            assert out[0] == acc


class TestEndomorphisms:
    def test_psi(self):
        ps = rand_g2(4)
        out = unpack_g2(j_psi(pack_g2(ps)))
        assert out == [C.psi(p) for p in ps]

    def test_g1_subgroup_check(self):
        good = rand_g1(3)
        # a point on the curve but not in the subgroup: multiply a random
        # curve point by r and check it is NOT the identity scaling... build
        # by scaling x until y^2 = x^3+4 has a root and point is out of G1
        bad = []
        x = 5
        while len(bad) < 2:
            y2 = F.Fq(x).pow(3) + C.B1
            y = y2.sqrt()
            if y is not None:
                cand = C.Point.from_affine(F.Fq(x), y, C.B1)
                if not C.g1_subgroup_check(cand):
                    bad.append(cand)
            x += 1
        ps = good + bad + [C.Point.infinity(C.B1)]
        out = np.asarray(j_g1_check(pack_g1(ps)))
        assert list(out) == [True, True, True, False, False, True]

    def test_g2_subgroup_check(self):
        good = rand_g2(2)
        bad = []
        x = 1
        while len(bad) < 1:
            xf = F.Fq2(x, 1)
            y2 = xf.square() * xf + C.B2
            y = y2.sqrt()
            if y is not None:
                cand = C.Point.from_affine(xf, y, C.B2)
                if not C.g2_subgroup_check(cand):
                    bad.append(cand)
            x += 1
        ps = good + bad
        out = np.asarray(j_g2_check(pack_g2(ps)))
        assert list(out) == [True, True, False]

    @pytest.mark.slow
    def test_g2_clear_cofactor(self):
        # random curve (not subgroup) points must land in G2.  Slow-marked
        # by the PR 15 compile-cost audit: the cofactor ladder re-lowers
        # every run (~23 s tier-1 wall); subgroup membership stays pinned
        # tier-1 by test_g2_subgroup_check, full HTC by test_ops_htc.
        pts = []
        x = 10
        while len(pts) < 2:
            xf = F.Fq2(x, 3)
            y2 = xf.square() * xf + C.B2
            y = y2.sqrt()
            if y is not None:
                pts.append(C.Point.from_affine(xf, y, C.B2))
            x += 1
        f = jax.jit(pt.g2_clear_cofactor)
        out = unpack_g2(f(pack_g2(pts)))
        for got, src in zip(out, pts):
            assert got == C.g2_clear_cofactor(src)
            assert C.g2_subgroup_check(got)
