"""ValidatorMonitor: registered validators' inclusions/proposals tracked
through real dev-chain imports (metrics/validatorMonitor.ts:165)."""

import asyncio

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL

CFG = ChainConfig(
    PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)


def test_monitor_tracks_inclusions_and_proposals():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.001)
        dev = DevChain(MINIMAL, CFG, 16, pool)
        mon = dev.chain.validator_monitor
        for i in range(16):
            mon.register_local_validator(i)
        # run two full epochs with attestations; epoch 1 is the first
        # FULL participation epoch (epoch 0's slot-0 committee never gets
        # an attestation round in the dev loop)
        await dev.run(2 * MINIMAL.SLOTS_PER_EPOCH)
        s0 = mon.epoch_summary(0)
        assert s0["registered"] == 16
        assert s0["attested"] == 14, f"missed: {s0['missed']}"
        s1 = mon.epoch_summary(1)
        assert s1["attested"] == 16, f"missed: {s1['missed']}"
        assert s1["avg_inclusion_delay"] >= 1.0
        assert len(s1["proposals"]) > 0
        # unregistered monitor reports nothing
        mon2_summary = dev.chain.validator_monitor.epoch_summary(99)
        assert mon2_summary["attested"] == 0
        pool.close()

    asyncio.run(main())
