"""Durable AOT executable store (ISSUE 9, docs/aot.md).

Discipline: every store lives under tmp_path (NEVER the repo-local
tier-1 store), every compiled program is a tiny jit (ms to build — far
under the conftest compile-guard threshold, so this module stays off the
compile whitelist), and verifier tests use bucket 5 + popped memo keys
so nothing leaks into other modules' program caches.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import pickle
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lodestar_tpu.aot.store import (
    AotExecutableStore,
    acquire_lockfile,
    entry_key,
    ops_content_hash,
    release_lockfile,
    topology_tag,
)
from lodestar_tpu.chaos import corrupt_file
from lodestar_tpu.crypto.bls.tpu_verifier import (
    _PROGRAM_MEMO,
    AotStoreMiss,
    TpuBlsVerifier,
)
from lodestar_tpu.forensics.journal import JOURNAL

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_compiled(scale: float = 2.0):
    """A real compiled executable that costs ms, not minutes."""
    fn = jax.jit(lambda x: x * scale)
    return fn.lower(jax.ShapeDtypeStruct((4,), jnp.float32)).compile()


def journal_since(seq0):
    return [e for e in JOURNAL.events() if e["seq"] >= seq0]


def kinds_since(seq0):
    return [e["kind"] for e in journal_since(seq0)]


# ---------------------------------------------------------------------------
# round-trip
# ---------------------------------------------------------------------------


class TestRoundTrip:
    def test_save_load_verdict_equivalence(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        live = tiny_compiled(3.0)
        x = np.arange(4, dtype=np.float32)
        expected = np.asarray(live(x))
        assert store.save("xla_full", 4, "default", live) is not None
        # a FRESH store instance (new manifest read) must serve an
        # executable producing the identical output
        fresh = AotExecutableStore(path=str(tmp_path))
        loaded = fresh.load("xla_full", 4, "default")
        assert loaded is not None
        np.testing.assert_array_equal(np.asarray(loaded(x)), expected)
        assert fresh.hits == 1 and fresh.corrupt == 0

    def test_round_trip_survives_a_new_process(self, tmp_path):
        """serialize -> NEW process -> deserialize -> identical output
        (the restart-survival contract, minus the verifier sugar)."""
        store = AotExecutableStore(path=str(tmp_path))
        live = tiny_compiled(5.0)
        x = np.arange(4, dtype=np.float32)
        expected = np.asarray(live(x)).tolist()
        assert store.save("xla_full", 4, "default", live) is not None
        xla_flags = os.environ.get("XLA_FLAGS", "")
        code = (
            "import os, sys, json\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            f"os.environ['XLA_FLAGS'] = {xla_flags!r}\n"
            "import numpy as np\n"
            "from lodestar_tpu.aot.store import AotExecutableStore\n"
            f"store = AotExecutableStore(path={str(tmp_path)!r})\n"
            "fn = store.load('xla_full', 4, 'default')\n"
            "assert fn is not None, 'store missed in the new process'\n"
            "print(json.dumps(np.asarray(fn(np.arange(4, dtype=np.float32))).tolist()))\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr[-800:]
        assert json.loads(out.stdout.strip().splitlines()[-1]) == expected

    def test_absent_key_is_a_plain_miss(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        seq0 = JOURNAL.seq
        assert store.load("xla_full", 4, "default") is None
        assert store.misses == 1 and store.corrupt == 0 and store.skew == 0
        assert "aot.corrupt" not in kinds_since(seq0)

    def test_disabled_store_is_inert(self):
        store = AotExecutableStore(path=None)
        assert store.load("xla_full", 4, "default") is None
        assert store.save("xla_full", 4, "default", object()) is None
        assert store.stats()["entries"] == 0


# ---------------------------------------------------------------------------
# crash consistency + integrity
# ---------------------------------------------------------------------------


class TestCrashConsistency:
    def test_orphan_temp_from_killed_writer_is_ignored(self, tmp_path):
        """The atomic-write crash window: payload temp written, rename
        never happened — the loader must not even see it (the manifest,
        written last, is the only index it trusts)."""
        store = AotExecutableStore(path=str(tmp_path))
        assert store.save("xla_full", 4, "default", tiny_compiled()) is not None
        orphan = tmp_path / "entries" / "deadbeef.aotx.12345.tmp"
        orphan.write_bytes(b"half-written garbage")
        fresh = AotExecutableStore(path=str(tmp_path))
        assert fresh.load("xla_full", 4, "default") is not None
        assert fresh.corrupt == 0
        sweep = fresh.verify()
        assert sweep["orphans"] == [orphan.name]
        assert fresh.sweep_orphans() == 1
        assert not orphan.exists()

    def test_checksum_rejection_quarantines(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        key = store.save("xla_full", 4, "default", tiny_compiled())
        rel = store.keys()[key]["file"]
        corrupt_file(str(tmp_path / rel), seed=7)
        seq0 = JOURNAL.seq
        fresh = AotExecutableStore(path=str(tmp_path))
        assert fresh.load("xla_full", 4, "default") is None
        assert fresh.corrupt == 1
        assert "aot.corrupt" in kinds_since(seq0)
        # quarantined aside (evidence), dropped from the manifest, and
        # the next load is a cheap plain miss
        assert (tmp_path / (rel + ".quarantined")).exists()
        assert key not in fresh.keys()
        assert fresh.load("xla_full", 4, "default") is None
        assert fresh.corrupt == 1  # counted once, not per retry

    def test_version_skew_evicts(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        key = store.save("xla_full", 4, "default", tiny_compiled())
        mpath = tmp_path / "manifest.json"
        doc = json.loads(mpath.read_text())
        doc["entries"][key]["jax"] = "0.0.0-skewed"
        mpath.write_text(json.dumps(doc))
        seq0 = JOURNAL.seq
        fresh = AotExecutableStore(path=str(tmp_path))
        assert fresh.load("xla_full", 4, "default") is None
        assert fresh.skew == 1
        ev = [e for e in journal_since(seq0) if e["kind"] == "aot.skew"]
        assert ev and ev[0]["reason"] == "jax_version"
        assert key not in fresh.keys()  # evicted, file deleted
        assert not (tmp_path / doc["entries"][key]["file"]).exists()

    def test_ops_hash_skew_evicts(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        key = store.save("xla_full", 4, "default", tiny_compiled())
        mpath = tmp_path / "manifest.json"
        doc = json.loads(mpath.read_text())
        doc["entries"][key]["ops_hash"] = "feedfacefeedface"
        mpath.write_text(json.dumps(doc))
        fresh = AotExecutableStore(path=str(tmp_path))
        assert fresh.load("xla_full", 4, "default") is None
        assert fresh.skew == 1

    def test_truncated_manifest_survivable(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        store.save("xla_full", 4, "default", tiny_compiled())
        mpath = tmp_path / "manifest.json"
        blob = mpath.read_bytes()
        mpath.write_bytes(blob[: len(blob) // 2])
        seq0 = JOURNAL.seq
        fresh = AotExecutableStore(path=str(tmp_path))
        assert fresh.keys() == {}
        assert fresh.load("xla_full", 4, "default") is None
        ev = [e for e in journal_since(seq0) if e["kind"] == "aot.corrupt"]
        assert ev and ev[0]["what"] == "manifest"

    def test_corrupt_pickle_with_valid_checksum_quarantines(self, tmp_path):
        """A payload whose bytes match the manifest but whose pickle is
        poison (written corrupt at save time) still degrades cleanly."""
        store = AotExecutableStore(path=str(tmp_path))
        key = store.save("xla_full", 4, "default", tiny_compiled())
        rec = store.keys()[key]
        fpath = tmp_path / rec["file"]
        bad = pickle.dumps(("not", "an", "executable"))
        fpath.write_bytes(bad)
        mpath = tmp_path / "manifest.json"
        doc = json.loads(mpath.read_text())
        import hashlib

        doc["entries"][key]["sha256"] = hashlib.sha256(bad).hexdigest()
        mpath.write_text(json.dumps(doc))
        fresh = AotExecutableStore(path=str(tmp_path))
        assert fresh.load("xla_full", 4, "default") is None
        assert fresh.corrupt == 1


# ---------------------------------------------------------------------------
# lockfile
# ---------------------------------------------------------------------------


class TestLockfile:
    def test_contended_save_bypasses_bounded(self, tmp_path):
        """Another LIVE writer holds the lock: the save waits its bound,
        then bypasses (skips) — never stalls, never raises."""
        store = AotExecutableStore(path=str(tmp_path), lock_wait_s=0.2)
        lock = tmp_path / "store.lock"
        lock.write_text(json.dumps({"pid": os.getpid(), "wall": 0}))
        seq0 = JOURNAL.seq
        t0 = time.monotonic()
        assert store.save("xla_full", 4, "default", tiny_compiled()) is None
        assert time.monotonic() - t0 < 3.0
        assert store.lock_bypasses == 1
        assert "aot.lock_busy" in kinds_since(seq0)
        # release: the next save goes through
        lock.unlink()
        assert store.save("xla_full", 4, "default", tiny_compiled()) is not None

    def test_stale_lock_from_dead_pid_is_broken(self, tmp_path):
        """A writer that died mid-save must not wedge the store: its
        lockfile names a dead pid and is reclaimed immediately."""
        p = multiprocessing.get_context("spawn").Process(target=int)
        p.start()
        p.join(30)
        dead_pid = p.pid
        lock = tmp_path / "store.lock"
        lock.write_text(json.dumps({"pid": dead_pid, "wall": 0}))
        t0 = time.monotonic()
        assert acquire_lockfile(str(lock), timeout_s=5.0)
        assert time.monotonic() - t0 < 2.0
        release_lockfile(str(lock))

    def test_unreadable_lock_is_not_broken(self, tmp_path):
        """An EMPTY lockfile is what a contender sees in the window
        between the holder's O_EXCL create and its json.dump — that race
        must wait out the bound, never break a possibly-live lock."""
        lock = tmp_path / "store.lock"
        lock.write_text("")
        t0 = time.monotonic()
        assert not acquire_lockfile(str(lock), timeout_s=0.2)
        assert 0.15 < time.monotonic() - t0 < 3.0
        assert lock.exists()  # never unlinked

    def test_save_on_unwritable_store_never_raises(self, tmp_path):
        """The store's contract: persistence trouble costs a recompile,
        never a raise into warmup.  A store path whose parent is a plain
        FILE can never be created (ENOTDIR — chmod tricks don't work
        under root) — save must bypass, not raise."""
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("")
        store = AotExecutableStore(
            path=str(blocker / "store"), lock_wait_s=0.1
        )
        assert store.save("xla_full", 4, "default", tiny_compiled()) is None
        assert store.load("xla_full", 4, "default") is None  # plain miss

    def test_loads_take_no_lock(self, tmp_path):
        store = AotExecutableStore(path=str(tmp_path))
        store.save("xla_full", 4, "default", tiny_compiled())
        (tmp_path / "store.lock").write_text(
            json.dumps({"pid": os.getpid(), "wall": 0})
        )
        t0 = time.monotonic()
        assert store.load("xla_full", 4, "default") is not None
        assert time.monotonic() - t0 < 1.0


# ---------------------------------------------------------------------------
# verifier integration (tiny fake kernel, bucket 5 — collision-proof with
# every real program key; memo keys popped on teardown)
# ---------------------------------------------------------------------------


BUCKET = 5


@pytest.fixture
def tiny_verifier_factory():
    built = []

    def build(store, **kw):
        kw.setdefault("buckets", (BUCKET,))
        kw.setdefault("platform", "cpu")
        kw.setdefault("fused", False)
        kw.setdefault("host_final_exp", False)
        v = TpuBlsVerifier(aot_store=store, **kw)
        v._kernel = lambda key: (lambda *a: jnp.asarray(True))
        built.append(v)
        return v

    yield build
    # hygiene: our fake programs must not outlive this module in the
    # process-wide memo (a real test asking for the same key would get
    # a stub verdict)
    for v in built:
        for ex in v._executors:
            for key in list(ex.compiled):
                _PROGRAM_MEMO.pop(v._memo_key(key, ex), None)
            ex.compiled.clear()


def make_sets(n):
    from lodestar_tpu.crypto.bls.api import interop_secret_key
    from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet

    out = []
    for i in range(n):
        sk = interop_secret_key(i % 8)
        msg = bytes([i, 0]) * 16
        out.append(SingleSignatureSet(
            pubkey=sk.to_public_key(), signing_root=msg,
            signature=sk.sign(msg).to_bytes(),
        ))
    return out


class TestVerifierLadder:
    def test_warmup_saves_then_fresh_verifier_loads(self, tmp_path,
                                                    tiny_verifier_factory):
        store = AotExecutableStore(path=str(tmp_path))
        v1 = tiny_verifier_factory(store)
        v1.warmup()
        assert store.saves == 1
        live_verdict = v1.verify_signature_sets(make_sets(2))
        # fresh verifier + cleared memo: the ONLY program source is the
        # store — and its verdict must match the live-compiled one
        for ex in v1._executors:
            for key in list(ex.compiled):
                _PROGRAM_MEMO.pop(v1._memo_key(key, ex), None)
        store2 = AotExecutableStore(path=str(tmp_path))
        v2 = tiny_verifier_factory(store2)
        v2.warmup()
        assert store2.hits == 1
        key = (BUCKET, False, False)
        assert key in v2._executors[0].compiled
        assert v2.verify_signature_sets(make_sets(2)) == live_verdict is True

    def test_dispatch_cold_path_loads_from_store(self, tmp_path,
                                                 tiny_verifier_factory):
        store = AotExecutableStore(path=str(tmp_path))
        v1 = tiny_verifier_factory(store)
        v1.warmup()
        for ex in v1._executors:
            for key in list(ex.compiled):
                _PROGRAM_MEMO.pop(v1._memo_key(key, ex), None)
        store2 = AotExecutableStore(path=str(tmp_path))
        v2 = tiny_verifier_factory(store2)
        # no warmup: dispatch's _fn walks memo -> store directly
        assert v2.verify_signature_sets(make_sets(2)) is True
        assert store2.hits == 1

    def test_load_only_empty_store_full_ladder(self, tmp_path,
                                               tiny_verifier_factory):
        """The acceptance contract: load-only warmup over an EMPTY store
        never compiles — fused -> XLA -> native with exactly one
        bls.degrade journal event + bls_degrade_total increment per hop,
        then every verdict rides the native rung."""
        from lodestar_tpu.metrics import create_metrics

        class StubNative:
            calls = 0

            def verify_signature_sets(self, sets):
                StubNative.calls += 1
                return True

        metrics = create_metrics()
        store = AotExecutableStore(path=str(tmp_path))
        v = tiny_verifier_factory(store, fused=True, load_only=True,
                                  native_verifier=StubNative())
        v.metrics = metrics
        seq0 = JOURNAL.seq
        v.warmup()
        degrades = [e for e in journal_since(seq0) if e["kind"] == "bls.degrade"]
        assert [(e["where"], e["tier"]) for e in degrades] == [
            ("warmup", "xla"), ("warmup", "native"),
        ]
        text = metrics.reg.expose().decode()
        assert 'lodestar_bls_degrade_total{tier="xla",where="warmup"} 1.0' in text
        assert 'lodestar_bls_degrade_total{tier="native",where="warmup"} 1.0' in text
        # never compiled: no program materialized anywhere
        assert all(not ex.compiled for ex in v._executors)
        assert v._native_tier_only
        # verdicts ride the native rung quietly (no per-batch degrade)
        before = len([e for e in JOURNAL.events() if e["kind"] == "bls.degrade"])
        assert v.verify_signature_sets(make_sets(2)) is True
        assert StubNative.calls == 1
        after = len([e for e in JOURNAL.events() if e["kind"] == "bls.degrade"])
        assert after == before

    def test_load_only_populated_store_serves_without_compiling(
            self, tmp_path, tiny_verifier_factory):
        store = AotExecutableStore(path=str(tmp_path))
        v1 = tiny_verifier_factory(store)
        v1.warmup()
        for ex in v1._executors:
            for key in list(ex.compiled):
                _PROGRAM_MEMO.pop(v1._memo_key(key, ex), None)
        seq0 = JOURNAL.seq
        store2 = AotExecutableStore(path=str(tmp_path))
        v2 = tiny_verifier_factory(store2, load_only=True)
        v2.warmup()
        assert store2.hits == 1 and not v2._native_tier_only
        assert "bls.degrade" not in kinds_since(seq0)
        assert v2.verify_signature_sets(make_sets(2)) is True

    def test_load_only_fn_miss_raises_typed(self, tmp_path,
                                            tiny_verifier_factory):
        store = AotExecutableStore(path=str(tmp_path))
        v = tiny_verifier_factory(store, load_only=True)
        with pytest.raises(AotStoreMiss):
            v._fn(BUCKET)

    def test_aot_load_ledgered_as_its_own_kind(self, tmp_path,
                                               tiny_verifier_factory):
        """The compile ledger's new classification: a store-served
        program records ``aot_load`` — not cold, not warm_load, and
        crucially not an in-process ``hit``."""
        from lodestar_tpu.observatory.compile_ledger import COMPILE_LEDGER

        store = AotExecutableStore(path=str(tmp_path))
        v1 = tiny_verifier_factory(store)
        v1.warmup()
        for ex in v1._executors:
            for key in list(ex.compiled):
                _PROGRAM_MEMO.pop(v1._memo_key(key, ex), None)
        store2 = AotExecutableStore(path=str(tmp_path))
        v2 = tiny_verifier_factory(store2)
        v2.warmup()
        summary = COMPILE_LEDGER.session_summary()
        assert "aot_load" in summary.get("xla_full", {})


class TestCpuCodegenGate:
    def test_small_payloads_always_pass(self):
        from lodestar_tpu.aot.store import _payload_loadable_cross_process

        assert _payload_loadable_cross_process(1024)

    def test_big_cpu_payload_needs_split_flag(self, monkeypatch):
        """A > 8 MB CPU payload from a parallel-codegen process would be
        unloadable in every OTHER process ('Symbols not found') — the
        save gate must refuse it unless the compiling process pinned
        --xla_cpu_parallel_codegen_split_count=1."""
        from lodestar_tpu.aot.store import (
            CPU_SAVE_MAX_BYTES,
            CPU_SPLIT_FLAG,
            _payload_loadable_cross_process,
        )

        big = CPU_SAVE_MAX_BYTES + 1
        monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        assert not _payload_loadable_cross_process(big)
        monkeypatch.setenv(
            "XLA_FLAGS",
            f"--xla_force_host_platform_device_count=8 {CPU_SPLIT_FLAG}",
        )
        assert _payload_loadable_cross_process(big)


class TestKeySchema:
    def test_entry_key_components(self):
        key = entry_key("cpux8", "fused_split", 128, "tpu:3",
                        jax_version="9.9.9", ops_hash="abc123")
        assert key == "cpux8|fused_split|b128|tpu:3|jax9.9.9|abc123"

    def test_ops_hash_stable_and_topology_shaped(self):
        assert ops_content_hash() == ops_content_hash()
        tag = topology_tag()
        platform, _, count = tag.rpartition("x")
        assert platform and count.isdigit()
