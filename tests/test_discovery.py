"""Discovery: signed node records, FINDNODE propagation, and the
network integration that dials discovered peers (peers/discover.ts role;
VERDICT r3 missing item 3)."""

import asyncio

from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.network.discovery import DiscoveryService, NodeRecord


def test_record_signature_and_forgery():
    sk = interop_secret_key(1)
    rec = NodeRecord(
        seq=1, pubkey=sk.to_public_key().to_bytes(), ip="127.0.0.1",
        tcp_port=9000, udp_port=9001,
    ).sign(sk)
    assert rec.verify_signature()
    decoded = NodeRecord.decode(rec.encode())
    assert decoded.verify_signature()
    assert decoded.node_id == rec.node_id
    # forging another identity's record fails verification
    forged = NodeRecord.decode(rec.encode())
    forged.tcp_port = 6666  # tamper
    assert not forged.verify_signature()
    other = interop_secret_key(2)
    stolen = NodeRecord(
        seq=9, pubkey=other.to_public_key().to_bytes(), ip="10.0.0.1",
        tcp_port=1, udp_port=1,
    ).sign(sk)  # signed by the WRONG key
    assert not stolen.verify_signature()


def test_three_node_transitive_discovery():
    async def main():
        found = {"a": [], "b": [], "c": []}

        svcs = {}
        for name, idx in (("a", 1), ("b", 2), ("c", 3)):
            svc = DiscoveryService(
                interop_secret_key(idx), tcp_port=9000 + idx,
                on_peer=lambda rec, _n=name: found[_n].append(rec),
            )
            await svc.listen(0)
            svcs[name] = svc

        # topology: A knows B; C knows B. A must learn C through B.
        svcs["a"].add_bootstrap("127.0.0.1", svcs["b"].udp_port)
        svcs["c"].add_bootstrap("127.0.0.1", svcs["b"].udp_port)
        await asyncio.sleep(0.3)
        # B now knows both; A asks B for nodes
        svcs["a"].find_nodes()
        for _ in range(50):
            if len(svcs["a"].table) >= 2:
                break
            await asyncio.sleep(0.1)
        ids_a = {rec.pubkey for rec in (e.record for e in svcs["a"].table.values())}
        assert svcs["c"].record.pubkey in ids_a, "A never learned about C"
        assert svcs["b"].record.pubkey in ids_a
        assert any(r.pubkey == svcs["c"].record.pubkey for r in found["a"])

        # subnet advertisement rides the record
        svcs["c"].update_subnets([False] * 63 + [True], [True, False, False, False])
        svcs["a"].find_nodes()
        await asyncio.sleep(0.3)
        c_entry = svcs["a"].table.get(svcs["c"].record.node_id)
        # seq bumped -> updated record replaces the old one
        assert c_entry is not None and c_entry.record.attnets[7] & 0x80

        for svc in svcs.values():
            await svc.close()

    asyncio.run(main())


def test_network_dials_discovered_peers():
    async def main():
        from lodestar_tpu.chain.bls_pool import BlsBatchPool
        from lodestar_tpu.chain.handlers import GossipHandlers
        from lodestar_tpu.config.chain_config import ChainConfig
        from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier
        from lodestar_tpu.network import Network
        from lodestar_tpu.node.dev_chain import DevChain
        from lodestar_tpu.params import MINIMAL

        cfg = ChainConfig(
            PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
            MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
            ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
        )
        pools, nets = [], []
        for i in range(2):
            pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
            dev = DevChain(MINIMAL, cfg, 16, pool)
            net = Network(MINIMAL, dev.chain, GossipHandlers(dev.chain))
            await net.listen(0)
            pools.append(pool)
            nets.append(net)
        # discovery: B bootstraps off A's udp endpoint; B should then DIAL
        # A's tcp listener automatically
        udp_a = await nets[0].enable_discovery(interop_secret_key(11))
        await nets[1].enable_discovery(
            interop_secret_key(12), bootstrap=[("127.0.0.1", udp_a)]
        )
        for _ in range(80):
            if nets[1].peer_manager.peers and nets[0].peer_manager.peers:
                break
            await asyncio.sleep(0.1)
        assert nets[1].peer_manager.peers, "B never dialed discovered peer A"
        assert nets[0].peer_manager.peers, "A never saw B connect"
        for net in nets:
            await net.close()
        for pool in pools:
            pool.close()

    asyncio.run(main())
