"""Fused Pallas Fq2 kernels vs the bigint oracle and the XLA library.

Interpret mode on CPU (every run); the compiled Mosaic path is exercised
by the round probes and, once wired into the dispatch, by the TPU
suites.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lodestar_tpu.crypto.bls import fields as F
from lodestar_tpu.ops import pallas_tower as pt
from lodestar_tpu.ops import tower

B = 8


def _rand_fq2(n, seed):
    """Reuses the library's limb encoding (tower.fq2_const) so a
    representation change cannot silently diverge this test."""
    rng = np.random.default_rng(seed)
    vals = [
        (int.from_bytes(rng.bytes(48), "big") % F.P,
         int.from_bytes(rng.bytes(48), "big") % F.P)
        for _ in range(n)
    ]
    arr = np.stack([tower.fq2_const(F.Fq2(c0, c1)) for c0, c1 in vals])
    return vals, jnp.asarray(arr)


def _to_fq2(row):
    return tower.fq2_to_oracle(row)


def test_fq2_mul_matches_oracle_and_library():
    av, a = _rand_fq2(B, 21)
    bv, b = _rand_fq2(B, 22)
    out = np.asarray(pt.fq2_mul(a, b, interpret=True))
    lib = np.asarray(tower.fq2_mul(a, b))
    assert out.max() <= 256  # semi-strict contract
    for i in range(B):
        want = F.Fq2(*av[i]) * F.Fq2(*bv[i])
        assert _to_fq2(out[i]) == want, i
        assert _to_fq2(lib[i]) == want, i  # library sanity


def test_fq2_sqr_matches_oracle():
    av, a = _rand_fq2(B, 23)
    out = np.asarray(pt.fq2_sqr(a, interpret=True))
    assert out.max() <= 256
    for i in range(B):
        v = F.Fq2(*av[i])
        assert _to_fq2(out[i]) == v * v, i


def test_fused_outputs_compose():
    """Semi-strict outputs feed back in as inputs (the chain shape the
    Miller loop needs): ((a*b)^2) via fused kernels == oracle."""
    av, a = _rand_fq2(B, 24)
    bv, b = _rand_fq2(B, 25)
    out = pt.fq2_sqr(pt.fq2_mul(a, b, interpret=True), interpret=True)
    for i in range(B):
        prod = F.Fq2(*av[i]) * F.Fq2(*bv[i])
        assert _to_fq2(np.asarray(out)[i]) == prod * prod, i


def test_semi_strict_edge_digits():
    """Inputs at the digit-256 boundary (the semi-strict contract the
    bound analysis hinges on: 50*256*256 must fit the mul's 2^22 carry
    bound) must still produce the oracle value."""
    a = jnp.asarray(np.full((1, 2, pt.NL), 256.0, np.float32))
    want = _to_fq2(np.asarray(a)[0])  # value of the redundant encoding
    out = np.asarray(pt.fq2_mul(a, a, interpret=True))
    assert out.max() <= 256
    assert _to_fq2(out[0]) == want * want
    out2 = np.asarray(pt.fq2_sqr(a, interpret=True))
    assert _to_fq2(out2[0]) == want * want


@pytest.mark.slow
def test_fq6_mul_matches_oracle():
    # slow-marked by the PR 15 compile-cost audit: the interpret-mode tower
    # multiply re-lowers every run (~14 s tier-1 wall); pallas coverage
    # stays pinned tier-1 by the fq2 tests and test_pallas_fuse.py
    rng = np.random.default_rng(41)

    def rand_fq6():
        return F.Fq6(*[
            F.Fq2(int.from_bytes(rng.bytes(48), "big") % F.P,
                  int.from_bytes(rng.bytes(48), "big") % F.P)
            for _ in range(3)
        ])

    avals = [rand_fq6() for _ in range(4)]
    bvals = [rand_fq6() for _ in range(4)]
    a = jnp.asarray(np.stack([
        np.stack([tower.fq2_const(v.c0), tower.fq2_const(v.c1), tower.fq2_const(v.c2)])
        for v in avals
    ]))
    b = jnp.asarray(np.stack([
        np.stack([tower.fq2_const(v.c0), tower.fq2_const(v.c1), tower.fq2_const(v.c2)])
        for v in bvals
    ]))
    out = np.asarray(pt.fq6_mul(a, b, interpret=True))
    assert out.max() <= 256
    for i in range(4):
        want = avals[i] * bvals[i]
        got = tower.fq6_to_oracle(out[i])
        assert got == want, i
    # library agreement too
    lib = np.asarray(tower.fq6_mul(a, b))
    for i in range(4):
        assert tower.fq6_to_oracle(lib[i]) == avals[i] * bvals[i], i


@pytest.mark.slow
def test_fq12_mul_matches_oracle():
    # slow-marked with test_fq6_mul_matches_oracle (same audit; ~23 s)
    rng = np.random.default_rng(47)

    def rand_fq12():
        def f2():
            return F.Fq2(int.from_bytes(rng.bytes(48), "big") % F.P,
                         int.from_bytes(rng.bytes(48), "big") % F.P)
        return F.Fq12(F.Fq6(f2(), f2(), f2()), F.Fq6(f2(), f2(), f2()))

    avals = [rand_fq12() for _ in range(2)]
    bvals = [rand_fq12() for _ in range(2)]
    a = jnp.asarray(np.stack([tower.fq12_const(v) for v in avals]))
    b = jnp.asarray(np.stack([tower.fq12_const(v) for v in bvals]))
    out = np.asarray(pt.fq12_mul(a, b, interpret=True))
    assert out.max() <= 256
    for i in range(2):
        want = avals[i] * bvals[i]
        assert tower.fq12_to_oracle(out[i]) == want, i
    # library agreement
    lib = np.asarray(tower.fq12_mul(a, b))
    for i in range(2):
        assert tower.fq12_to_oracle(lib[i]) == avals[i] * bvals[i], i
