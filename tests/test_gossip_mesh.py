"""Gossipsub mesh semantics: degree-bounded fanout, lazy IHAVE/IWANT
recovery, score-driven GRAFT/PRUNE and eviction.

Reference: packages/beacon-node/src/network/gossip/gossipsub.ts:84 (the
scored mesh), scoringParameters.ts (D parameters, thresholds, invalid-
message weights).
"""

import asyncio

import pytest

from lodestar_tpu.chain.validation import GossipAction, GossipValidationError
from lodestar_tpu.network.gossip import (
    GOSSIP_D,
    GOSSIP_D_HIGH,
    GRAYLIST_THRESHOLD,
    GossipRouter,
    message_id,
)
from lodestar_tpu.network.wire import Wire


def make_cluster(n, topic="t", handler_factory=None):
    """Fully-connected in-process cluster of routers; returns
    (routers, delivered) where delivered[i] counts handler invocations and
    routers[i].sent_msgs counts frames that left node i."""
    routers = [GossipRouter() for _ in range(n)]
    delivered = [0] * n

    for i, r in enumerate(routers):
        r.sent_msgs = 0

        async def handler(data, _i=i):
            delivered[_i] += 1

        r.subscribe(topic, handler_factory(i) if handler_factory else handler)

    for i, ri in enumerate(routers):
        for j, rj in enumerate(routers):
            if i == j:
                continue

            def mk(src, dst, dst_router):
                async def send_msg(t, data, _s=src, _d=dst):
                    routers[_s].sent_msgs += 1
                    await dst_router.on_message(t, data, from_peer=f"n{_s}")

                async def send_ctrl(ctrl, _s=src):
                    await dst_router.on_control(f"n{_s}", Wire.decode_gossip_ctrl(
                        Wire.encode_gossip_ctrl(ctrl)
                    ))

                return send_msg, send_ctrl

            sm, sc = mk(i, j, rj)
            ri.add_peer(f"n{j}", sm, sc)
    return routers, delivered


def test_mesh_bounds_fanout_and_delivers():
    """16 fully-connected nodes: after heartbeats the mesh degree is
    within [0, D_HIGH], a publish reaches every node, and per-node relay
    fanout is bounded by D (not by peer count)."""

    async def run():
        n = 16
        routers, delivered = make_cluster(n)
        # announce subscriptions both ways
        for i, r in enumerate(routers):
            for j in range(n):
                if j != i:
                    await r.announce_subscriptions(f"n{j}")
        for _ in range(3):
            for r in routers:
                await r.heartbeat()
        for r in routers:
            assert len(r.mesh["t"]) <= GOSSIP_D_HIGH
            assert len(r.mesh["t"]) >= 1
        for r in routers:
            r.sent_msgs = 0
        await routers[0].publish("t", b"payload-1")
        await asyncio.sleep(0)
        # every node except the publisher (whose local handler is not part
        # of publish) received it exactly once (dedup)
        assert delivered[0] == 0 and all(d == 1 for d in delivered[1:]), delivered
        # fanout bound: each node sent to at most D_HIGH peers (mesh), far
        # below the flood bound of n-1 = 15
        for i, r in enumerate(routers):
            assert r.sent_msgs <= GOSSIP_D_HIGH, (i, r.sent_msgs)

    asyncio.run(run())


def test_ihave_iwant_recovers_missed_message():
    async def run():
        a, b = GossipRouter(), GossipRouter()
        log = []

        async def h(data):
            log.append(data)

        a.subscribe("t", h)

        async def hb(data):
            log.append(b"b:" + data)

        b.subscribe("t", hb)
        # connect ONLY the control plane a->b and message plane a->b, so b
        # cannot receive the original publish (a's mesh is empty of b until
        # graft; simulate a missed message instead)
        sent = []

        async def a_send_msg(t, d):
            sent.append((t, d))
            await b.on_message(t, d, from_peer="a")

        async def a_send_ctrl(c):
            await b.on_control("a", Wire.decode_gossip_ctrl(Wire.encode_gossip_ctrl(c)))

        async def b_send_msg(t, d):
            await a.on_message(t, d, from_peer="b")

        async def b_send_ctrl(c):
            await a.on_control("b", Wire.decode_gossip_ctrl(Wire.encode_gossip_ctrl(c)))

        a.add_peer("b", a_send_msg, a_send_ctrl)
        b.add_peer("a", b_send_msg, b_send_ctrl)
        await a.announce_subscriptions("b")
        await b.announce_subscriptions("a")
        # a learns a message while b's mesh hasn't formed: seed it directly
        data = b"missed-message"
        a.seen.check_and_add(message_id("t", data))
        a._mcache_put(message_id("t", data), "t", data)
        # b is subscribed but NOT in a's mesh: the heartbeat's lazy-gossip
        # phase IHAVEs non-mesh subscribers, b answers IWANT, a serves from
        # mcache (call the gossip phase directly — a full heartbeat would
        # first graft b, the under-filled-mesh repair, which is also
        # correct but not the path under test)
        a.mesh["t"].clear()
        await a._emit_gossip()
        await asyncio.sleep(0)
        assert any(d == b"b:" + data for d in log), log
        assert a.iwant_received >= 1

    asyncio.run(run())


def test_bad_peer_pruned_and_evicted():
    """A peer relaying REJECTed messages turns score-negative (pruned from
    the mesh) and eventually crosses the graylist threshold (evicted)."""

    async def run():
        evicted = []
        r = GossipRouter(on_evict=lambda k, s: evicted.append((k, s)))
        topic = "/eth2/00000000/beacon_block/ssz_snappy"  # weight 0.5

        async def bad_handler(data):
            raise GossipValidationError(GossipAction.REJECT, "bad")

        r.subscribe(topic, bad_handler)

        async def noop_msg(t, d):
            pass

        async def noop_ctrl(c):
            pass

        r.add_peer("mallory", noop_msg, noop_ctrl)
        await r.on_control("mallory", {"sub": [topic], "graft": [topic]})
        assert "mallory" in r.mesh[topic]
        # invalid deliveries drive the quadratic topic penalty
        # (invalid_message_deliveries_weight = -140, block weight 0.5)
        for i in range(40):
            await r.on_message(topic, b"junk-%d" % i, from_peer="mallory")
        assert r.score("mallory") < GRAYLIST_THRESHOLD
        await r.heartbeat()
        assert "mallory" not in r.mesh[topic]
        assert evicted and evicted[0][0] == "mallory"

    asyncio.run(run())


def test_graft_rejected_when_not_subscribed():
    async def run():
        r = GossipRouter()
        prunes = []

        async def noop_msg(t, d):
            pass

        async def ctrl_sink(c):
            prunes.append(c)

        r.add_peer("p", noop_msg, ctrl_sink)
        await r.on_control("p", {"graft": ["unknown-topic"]})
        assert "unknown-topic" not in r.mesh
        assert any("prune" in c for c in prunes)

    asyncio.run(run())


def test_ctrl_wire_roundtrip():
    ctrl = {
        "sub": ["/eth2/aabbccdd/beacon_block/ssz_snappy"],
        "graft": ["t1", "t2"],
        "ihave": [("t1", [b"\x01" * 20, b"\x02" * 20])],
        "iwant": [b"\x03" * 20],
    }
    out = Wire.decode_gossip_ctrl(Wire.encode_gossip_ctrl(ctrl))
    assert out["sub"] == ctrl["sub"]
    assert out["graft"] == ctrl["graft"]
    assert out["ihave"] == ctrl["ihave"]
    assert out["iwant"] == ctrl["iwant"]
