"""Events SSE stream + head-tracking VC (VERDICT r3 item 9).

Done-criterion: the VC attests triggered by the head EVENT, not the
clock.  Reference: packages/api/src/beacon/routes/events.ts:20 and
validator/src/services/chainHeaderTracker.ts.
"""

import asyncio

from lodestar_tpu.api import RestApiServer
from lodestar_tpu.api.client import ApiClient
from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.validator import ChainHeaderTracker

CFG = ChainConfig(
    PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)


def test_events_stream_delivers_head_block_finalized():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, 16, pool)
        rest = RestApiServer(MINIMAL, dev.chain)
        port = await rest.listen(0)
        api = ApiClient("127.0.0.1", port)

        got = []

        async def consume():
            async for name, data in api.events("head,block"):
                got.append((name, data))
                if len(got) >= 4:
                    return

        consumer = asyncio.create_task(consume())
        await asyncio.sleep(0.2)  # let the subscription attach
        await dev.advance_slot(1, with_attestations=False)
        await dev.advance_slot(2, with_attestations=False)
        await asyncio.wait_for(consumer, 30.0)

        names = [n for n, _ in got]
        assert "block" in names and "head" in names
        heads = [d for n, d in got if n == "head"]
        assert heads[-1]["block"].startswith("0x")
        assert int(heads[-1]["slot"]) >= 1

        await rest.close()
        pool.close()

    asyncio.run(main())


def test_vc_attests_on_head_event_not_clock():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, 16, pool)
        rest = RestApiServer(MINIMAL, dev.chain)
        port = await rest.listen(0)
        api = ApiClient("127.0.0.1", port)

        tracker = ChainHeaderTracker(api)
        tracker.start()
        await asyncio.sleep(0.2)

        # the block for slot 1 is NOT produced yet: a clock-driven waiter
        # would burn its whole timeout; the event-driven one returns the
        # moment the block lands
        async def produce_later():
            await asyncio.sleep(0.5)
            await dev.advance_slot(1, with_attestations=False)

        producer = asyncio.create_task(produce_later())
        t0 = asyncio.get_event_loop().time()
        on_event = await tracker.wait_for_slot_head(1, timeout=20.0)
        waited = asyncio.get_event_loop().time() - t0
        await producer
        assert on_event, "head event never arrived"
        assert waited < 15.0, "tracker waited for the timeout, not the event"
        assert tracker.head_slot >= 1
        assert tracker.events_seen >= 1

        await tracker.stop()
        await rest.close()
        pool.close()

    asyncio.run(main())
