"""Hot-path span tracing (ISSUE 2): tracer semantics, Chrome export,
batch-correlated pipeline spans through the BLS pool, debug endpoints,
and the two standalone observability gates under tools/.

Deliberately trace-light: no jax.jit compiles — the real pack()
instrumentation is exercised host-side, and the dispatch/final-exp spans
through a stage-split fake verifier (the TpuBlsVerifier timing shape
without a device).
"""

import asyncio
import importlib.util
import json
import os
import threading
import time

import pytest

from lodestar_tpu import tracing
from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet
from lodestar_tpu.metrics import create_metrics
from lodestar_tpu.tracing import TRACER, SpanTracer, to_chrome_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


check_trace = _load_tool("check_trace")
check_metrics_coverage = _load_tool("check_metrics_coverage")


@pytest.fixture(autouse=True)
def _clean_tracer():
    """The module singleton must not leak state across tests (or into the
    rest of the suite)."""
    TRACER.disable()
    TRACER.clear()
    yield
    TRACER.disable()
    TRACER.clear()


def make_set(i, valid=True):
    sk = interop_secret_key(i)
    msg = bytes([i % 256]) * 32
    signer = sk if valid else interop_secret_key(i + 100)
    return SingleSignatureSet(
        pubkey=sk.to_public_key(),
        signing_root=msg,
        signature=signer.sign(msg).to_bytes(),
    )


class TestTracer:
    def test_disabled_records_nothing(self):
        tr = SpanTracer(capacity=8)
        tr.add_span("a", "x", 0, 10)
        tr.instant("b")
        with tr.span("c", "x"):
            pass
        assert len(tr) == 0
        assert tr.now() == 0  # disabled path never calls the clock

    def test_ring_buffer_evicts_oldest(self):
        tr = SpanTracer(capacity=4)
        tr.enable()
        for i in range(10):
            tr.add_span(f"s{i}", "x", i, i + 1)
        spans = tr.spans()
        assert len(spans) == 4
        assert [s.name for s in spans] == ["s6", "s7", "s8", "s9"]
        assert tr.dropped == 6

    def test_enable_resizes_and_span_fields(self):
        tr = SpanTracer(capacity=4)
        tr.enable(capacity=128)
        assert tr.capacity == 128
        t0 = time.monotonic_ns()
        with tr.span("work", "cat", cid=7, n=3):
            pass
        tr.instant("mark", slot=5)
        work, mark = tr.spans()
        assert work.name == "work" and work.cid == 7 and work.args == {"n": 3}
        assert work.ts_ns >= t0 and work.dur_ns >= 0
        assert work.tid == threading.get_ident()
        assert mark.instant and mark.args == {"slot": 5}

    def test_thread_safety_concurrent_writers(self):
        tr = SpanTracer(capacity=64)
        tr.enable()

        def write(k):
            for i in range(50):
                tr.add_span(f"t{k}", "x", tr.now())

        threads = [threading.Thread(target=write, args=(k,)) for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(tr) == 64
        assert tr.dropped == 4 * 50 - 64


class TestChromeExport:
    def test_export_schema_validates(self, tmp_path):
        tr = SpanTracer()
        tr.enable()
        with tr.span("bls.pack", "bls", cid=1, sets=4):
            pass
        tr.instant("clock.slot", cat="clock", slot=3)
        doc = to_chrome_trace(tr)
        assert check_trace.validate(doc) == []
        names = [e["name"] for e in doc["traceEvents"]]
        assert "process_name" in names and "thread_name" in names
        pack = next(e for e in doc["traceEvents"] if e["name"] == "bls.pack")
        assert pack["ph"] == "X" and pack["args"]["cid"] == 1 and pack["id"] == 1
        inst = next(e for e in doc["traceEvents"] if e["name"] == "clock.slot")
        assert inst["ph"] == "i"
        # the CLI entry accepts the dump too
        path = tmp_path / "trace.json"
        tracing.write_chrome_trace(tr, str(path))
        assert check_trace.main([str(path)]) == 0

    def test_validator_rejects_malformed(self):
        assert check_trace.validate(42)
        assert check_trace.validate({"nope": []})
        errs = check_trace.validate(
            {"traceEvents": [{"ph": "X", "name": "a", "ts": 1},  # no dur
                             {"name": "b"},  # no ph
                             {"ph": "i", "name": "c", "ts": 0, "pid": "x"}],
             "otherData": {"dropped_spans": 0}}
        )
        assert len(errs) == 3

    def test_validator_requires_drop_count_note(self):
        """Round 9: an object-form dump must say how many spans the ring
        evicted under it (otherData.dropped_spans) — a dump that cannot
        quantify its missing history is silently lying about coverage."""
        errs = check_trace.validate({"traceEvents": []})
        assert any("dropped_spans" in e for e in errs)
        assert check_trace.validate(
            {"traceEvents": [], "otherData": {"dropped_spans": 7}}
        ) == []
        # bare list-form dumps (no wrapper object) carry no note to check
        assert check_trace.validate([]) == []

    def test_pipeline_requirement(self):
        tr = SpanTracer()
        tr.enable()
        for cid in (1, 2):
            for name in check_trace.PIPELINE_SPANS:
                tr.add_span(name, "bls", 0, 1000, cid=cid)
        doc = to_chrome_trace(tr)
        assert check_trace.validate_pipeline(doc, 2) == []
        assert check_trace.validate_pipeline(doc, 3)  # only 2 batches
        # zero-duration spans don't count
        tr2 = SpanTracer()
        tr2.enable()
        for name in check_trace.PIPELINE_SPANS:
            tr2.add_span(name, "bls", 5, 5, cid=1)
        assert check_trace.validate_pipeline(to_chrome_trace(tr2), 1)


class StageTracedVerifier:
    """Stage-split fake with the TpuBlsVerifier timing shape AND its span
    emissions: pack blocks the calling thread, the 'device' computes in
    wall time after the async enqueue, result() syncs then pays the host
    final-exp cost.  Spans are stamped with the pool-assigned correlation
    id read from the contextvar — proving the id propagates through
    asyncio.to_thread into both halves of the flusher."""

    PACK_S = 0.02
    DEVICE_S = 0.04
    FINAL_S = 0.02

    def __init__(self):
        self.dispatched = 0
        self.stage_seconds = {"pack": 0.0, "dispatch": 0.0, "final_exp": 0.0}

    def verify_signature_sets_async(self, sets):
        cid = tracing.current_batch_id()
        t0 = TRACER.now()
        time.sleep(self.PACK_S)
        TRACER.add_span("bls.pack", "bls", t0, cid=cid, sets=len(sets))
        self.stage_seconds["pack"] += self.PACK_S
        t0 = TRACER.now()
        self.dispatched += 1
        ready_at = time.monotonic() + self.DEVICE_S
        TRACER.add_span("bls.dispatch", "bls", t0, cid=cid, bucket=len(sets))
        self.stage_seconds["dispatch"] += 1e-4
        outer = self

        class _Pending:
            def result(_self):
                rem = ready_at - time.monotonic()
                if rem > 0:
                    time.sleep(rem)  # device sync
                t0 = TRACER.now()
                time.sleep(outer.FINAL_S)
                TRACER.add_span(
                    "bls.final_exp", "bls", t0, cid=tracing.current_batch_id()
                )
                outer.stage_seconds["final_exp"] += outer.FINAL_S
                return True

        return _Pending()

    def verify_signature_sets(self, sets):
        return self.verify_signature_sets_async(sets).result()


class TestPoolPipelineSpans:
    def test_correlated_pipeline_spans_two_inflight_batches(self, tmp_path):
        """Acceptance: >=2 in-flight batches leave queue-wait / pack /
        dispatch / final-exp spans with non-zero durations under >=2
        distinct correlation ids, and the dump passes
        tools/check_trace.py --require-pipeline."""

        async def main():
            tracing.enable(1024)
            v = StageTracedVerifier()
            metrics = create_metrics()
            pool = BlsBatchPool(
                v, max_buffer_wait=0.004, pipeline_depth=3, metrics=metrics
            )
            # stagger pushes so the flusher drains three separate batches,
            # each landing while the previous batch is still packing
            jobs = [asyncio.create_task(pool.verify_signature_sets([make_set(0)]))]
            for i in (1, 2):
                await asyncio.sleep(StageTracedVerifier.PACK_S * 0.9)
                jobs.append(
                    asyncio.create_task(pool.verify_signature_sets([make_set(i)]))
                )
            assert await asyncio.gather(*jobs) == [True] * 3
            assert pool.inflight_peak >= 2
            pool.close()
            return pool, metrics

        pool, metrics = asyncio.run(main())

        spans = TRACER.spans()
        by_name = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        for name in ("bls.queue_wait", "bls.pack", "bls.dispatch",
                     "bls.final_exp", "pool.batch"):
            assert by_name.get(name), f"missing {name} spans: {sorted(by_name)}"
        cids = {s.cid for s in by_name["bls.pack"]}
        assert len(cids) >= 2, cids
        # every batch's stages share its correlation id
        for cid in cids:
            stages = {s.name for s in spans if s.cid == cid}
            assert {"bls.queue_wait", "bls.pack", "bls.dispatch",
                    "bls.final_exp", "pool.batch"} <= stages, (cid, stages)
        assert all(s.dur_ns > 0 for s in by_name["bls.pack"])

        path = str(tmp_path / "pipeline.json")
        tracing.write_chrome_trace(TRACER, path)
        assert check_trace.main([path, "--require-pipeline", "2"]) == 0

        # satellite 1: the orphaned counters are now gauges, set on flush
        text = metrics.reg.expose().decode()
        assert 'lodestar_bls_verifier_stage_seconds{stage="pack"}' in text
        assert "lodestar_bls_pool_inflight_peak" in text
        assert "lodestar_bls_pool_overlap_ratio" in text
        assert "lodestar_bls_pool_queue_wait_seconds_count" in text
        try:
            assert metrics.bls_pool_inflight_peak._value.get() >= 2
            assert metrics.bls_pool_overlap_ratio._value.get() > 1.0  # pipelined
        except AttributeError:  # prometheus absent -> noop metrics
            pass

    def test_disabled_tracer_records_nothing_on_hot_path(self):
        async def main():
            pool = BlsBatchPool(StageTracedVerifier(), max_buffer_wait=0.002)
            jobs = [pool.verify_signature_sets([make_set(i)]) for i in range(3)]
            assert await asyncio.gather(*jobs) == [True] * 3
            pool.close()

        asyncio.run(main())
        assert len(TRACER) == 0

    def test_real_pack_emits_span(self):
        """The real TpuBlsVerifier.pack instrumentation (host-only, no
        jit: packing is numpy + bigint + sha256)."""
        from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier

        tracing.enable(64)
        v = TpuBlsVerifier(platform="cpu")
        packed = v.pack([make_set(0), make_set(1)])
        assert packed is not None
        spans = [s for s in TRACER.spans() if s.name == "bls.pack"]
        assert len(spans) == 1
        assert spans[0].dur_ns > 0
        assert spans[0].args == {"sets": 2, "cache_hits": 0}
        assert spans[0].cid is None  # no pool context here

    def test_clock_slot_annotations(self):
        from lodestar_tpu.chain.clock import ManualClock

        tracing.enable(64)
        clock = ManualClock(0, 6, 8)
        clock.set_slot(9)
        marks = [s for s in TRACER.spans() if s.name == "clock.slot"]
        assert marks and marks[-1].args == {"slot": 9, "epoch": 1}

    def test_queue_drain_with_enqueue_time(self):
        from lodestar_tpu.utils.queue import JobItemQueue

        async def main():
            async def process(x):
                return x

            q = JobItemQueue(process, max_length=10, max_concurrency=0)
            tasks = [asyncio.create_task(q.push(i)) for i in range(2)]
            await asyncio.sleep(0)
            t_before = time.monotonic()
            batch = q.drain_batch(5, with_enqueue_time=True)
            assert [row[0] for row in batch] == [0, 1]
            assert all(len(row) == 3 for row in batch)
            for item, fut, t_enq in batch:
                assert t_enq <= t_before
                fut.set_result(item)
            assert await asyncio.gather(*tasks) == [0, 1]

        asyncio.run(main())


class TestDebugEndpoints:
    def _server(self, with_pool=True, with_registry=False):
        from lodestar_tpu.api.rest import RestApiServer
        from lodestar_tpu.params import MINIMAL

        class _StubChain:
            bls = None

        chain = _StubChain()
        metrics = create_metrics() if with_registry else None
        if with_pool:
            chain.bls = BlsBatchPool(StageTracedVerifier(), metrics=metrics)
        return RestApiServer(
            MINIMAL, chain, metrics_registry=metrics.reg if metrics else None,
            metrics=metrics,
        ), chain

    def test_traces_endpoint_json_and_chrome(self):
        tracing.enable(64)
        TRACER.add_span("bls.pack", "bls", 100, 2100, cid=5, sets=1)
        server, _ = self._server(with_pool=False)

        async def main():
            status, payload, ctype = await server._dispatch(
                "GET", "/eth/v1/lodestar/traces", b""
            )
            assert status == 200 and ctype == "application/json"
            assert payload["data"]["enabled"] is True
            assert payload["data"]["count"] == 1
            span = payload["data"]["spans"][0]
            assert span["name"] == "bls.pack" and span["cid"] == 5
            assert span["dur_us"] == 2.0

            status, raw, ctype = await server._dispatch(
                "GET", "/eth/v1/lodestar/traces?format=chrome", b""
            )
            assert status == 200
            doc = json.loads(raw.decode())
            assert check_trace.validate(doc) == []

        asyncio.run(main())

    def test_bls_stages_endpoint(self):
        server, chain = self._server(with_pool=True)
        chain.bls.verifier.stage_seconds["pack"] = 1.25
        chain.bls.inflight_peak = 3

        async def main():
            status, payload, _ = await server._dispatch(
                "GET", "/eth/v1/lodestar/bls_stages", b""
            )
            assert status == 200
            data = payload["data"]
            assert data["stage_seconds"]["pack"] == 1.25
            assert data["inflight_peak"] == 3
            assert data["verifier"] == "StageTracedVerifier"
            chain.bls.close()

        asyncio.run(main())

    def test_bls_stages_404_without_pool(self):
        server, _ = self._server(with_pool=False)

        async def main():
            status, payload, _ = await server._dispatch(
                "GET", "/eth/v1/lodestar/bls_stages", b""
            )
            assert status == 404

        asyncio.run(main())


class TestMetricsCoverageGate:
    def test_registry_metrics_all_covered(self):
        """CI gate: every metric in registry.py appears in a dashboard or
        a doc (tools/check_metrics_coverage.py, runnable standalone)."""
        report = check_metrics_coverage.check(REPO)
        assert len(report) >= 50  # the registry is substantial
        orphans = [
            m for m, cov in report.items()
            if not cov["dashboards"] and not cov["docs"]
        ]
        assert orphans == [], f"orphan metrics (add a panel or doc row): {orphans}"
        assert check_metrics_coverage.main(["--repo", REPO]) == 0

    def test_gate_catches_orphan(self, tmp_path):
        """The tool actually fails when a metric is unreferenced."""
        repo = tmp_path
        (repo / "lodestar_tpu" / "metrics").mkdir(parents=True)
        (repo / "lodestar_tpu" / "metrics" / "registry.py").write_text(
            's = r.gauge(\n    "lodestar_ghost_metric", "never shown anywhere"\n)\n'
        )
        (repo / "docs").mkdir()
        (repo / "docs" / "observability.md").write_text("# nothing here\n")
        assert check_metrics_coverage.main(["--repo", str(repo)]) == 1
