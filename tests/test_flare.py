"""flare ops CLI: self-slash commands land real slashings in the op pool.

Reference: packages/flare/src/cmds/selfSlashProposer.ts /
selfSlashAttester.ts — the slashings must be structurally valid enough
for the pool to pack them into the next block.
"""

import asyncio

from lodestar_tpu import flare
from lodestar_tpu.api import RestApiServer
from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL

CFG = ChainConfig(
    PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)


def test_self_slash_proposer_flows_into_pool():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, 16, pool)
        server = RestApiServer(MINIMAL, dev.chain)
        port = await server.listen(0)

        class Args:
            server = f"http://127.0.0.1:{port}"
            preset = "minimal"
            index_start = 3
            count = 2
            slot = 1

        sent = await flare.self_slash_proposer(Args)
        assert sent == 2
        slashings, _, _ = dev.chain.op_pool.get_slashings_and_exits(
            dev.chain.head_state()
        )
        assert {s.signed_header_1.message.proposer_index for s in slashings} == {3, 4}
        await server.close()
        return True

    assert asyncio.run(main())


def test_self_slash_attester_flows_into_pool():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, 16, pool)
        server = RestApiServer(MINIMAL, dev.chain)
        port = await server.listen(0)

        class Args:
            server = f"http://127.0.0.1:{port}"
            preset = "minimal"
            index_start = 0
            count = 3
            epoch = 0

        sent = await flare.self_slash_attester(Args)
        assert sent == 1
        _, att_slashings, _ = dev.chain.op_pool.get_slashings_and_exits(
            dev.chain.head_state()
        )
        assert len(att_slashings) == 1
        assert list(att_slashings[0].attestation_1.attesting_indices) == [0, 1, 2]
        await server.close()
        return True

    assert asyncio.run(main())
