"""Two-node loopback tests: reqresp handshake, range sync, gossip block
propagation, unknown-block resolution.

VERDICT r2 #6 done-criterion (node B range-syncs N epochs from node A and
reaches the same head); reference precedent:
beacon-node/test/sim/multiNodeSingleThread.test.ts and
network/reqresp e2e tests.
"""

import asyncio

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.chain.handlers import GossipHandlers
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.network import Network
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.ssz import Fields
from lodestar_tpu.sync import RangeSync, SyncState, UnknownBlockSync

CFG = ChainConfig(
    PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)
N = 16


def make_pair():
    """Two dev nodes sharing genesis (same interop keys/time)."""
    pool_a = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
    pool_b = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
    a = DevChain(MINIMAL, CFG, N, pool_a)
    b = DevChain(MINIMAL, CFG, N, pool_b)
    return a, b, pool_a, pool_b


def test_handshake_and_range_sync():
    async def main():
        a, b, pool_a, pool_b = make_pair()
        # node A advances 2.5 epochs; B stays at genesis
        await a.run(2 * MINIMAL.SLOTS_PER_EPOCH + 4, with_attestations=False)

        net_a = Network(MINIMAL, a.chain, GossipHandlers(a.chain))
        net_b = Network(MINIMAL, b.chain, GossipHandlers(b.chain))
        port = await net_a.listen(0)
        peer = await net_b.connect("127.0.0.1", port)

        # handshake stored A's status on B's peer record
        assert peer.status is not None
        assert peer.status.head_slot == a.chain.head_state().slot

        # ping + metadata round-trip
        assert await peer.reqresp.ping(7) == 7
        md = await peer.reqresp.metadata()
        assert md.seq_number == 0

        # range sync B -> A's head
        sync = RangeSync(MINIMAL, b.chain, net_b.peer_manager)
        imported = await sync.run_to_head()
        assert sync.state == SyncState.synced
        assert imported > 0
        assert b.chain.head_root == a.chain.head_root

        await net_b.close()
        await net_a.close()
        pool_a.close()
        pool_b.close()

    asyncio.run(main())


def test_gossip_block_propagation_and_unknown_parent():
    async def main():
        a, b, pool_a, pool_b = make_pair()
        net_a = Network(MINIMAL, a.chain, GossipHandlers(a.chain))
        net_b = Network(MINIMAL, b.chain, GossipHandlers(b.chain))
        port = await net_a.listen(0)
        await net_b.connect("127.0.0.1", port)

        # A produces a block for slot 1 and publishes it; B imports via the
        # gossip handler path
        signed = await a.produce_and_import_block(1)
        b.clock.set_slot(1)  # B's wall clock follows the net's slot
        n_sent = await net_a.publish_block(signed)
        assert n_sent == 1
        for _ in range(100):  # poll: import includes STF + batch verify
            if b.chain.head_root == a.chain.head_root:
                break
            await asyncio.sleep(0.1)
        assert b.chain.head_root == a.chain.head_root

        # A advances two more blocks silently, then publishes only the tip:
        # B resolves ancestors via blocks_by_root (unknown-block sync)
        s2 = await a.produce_and_import_block(2)
        s3 = await a.produce_and_import_block(3)
        b.clock.set_slot(3)
        # B hasn't seen s2; hand s3 to the resolver directly (the gossip
        # path would surface BlockError: unknown parent first)
        ub = UnknownBlockSync(MINIMAL, b.chain, net_b.peer_manager)
        # B needs a peer status to pick a sync peer
        peer_b = net_b.peer_manager.connected()[0]
        await net_b.peer_manager.handshake(peer_b, peer_b.reqresp.local_status())
        ok = await ub.resolve(s3)
        assert ok
        assert b.chain.head_root == a.chain.head_root

        await net_b.close()
        await net_a.close()
        pool_a.close()
        pool_b.close()

    asyncio.run(main())


def test_range_sync_survives_garbage_peer():
    """VERDICT r3 item 10 done-criterion: one peer serves garbage blocks,
    sync completes from the honest peer and the bad one is downscored."""

    async def main():
        a, b, pool_a, pool_b = make_pair()
        pool_c = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        c = DevChain(MINIMAL, CFG, N, pool_c)  # the syncing node
        await a.run(MINIMAL.SLOTS_PER_EPOCH + 4, with_attestations=False)
        # b mirrors a's chain so it can serve the same canonical blocks
        for slot in range(1, MINIMAL.SLOTS_PER_EPOCH + 5):
            root = a.chain.fork_choice.proto.get_ancestor(a.chain.head_root, slot)
            blk = a.chain.get_block_by_root(root) if root else None
            if blk is not None and blk.message.slot == slot:
                b.clock.set_slot(slot)
                await b.chain.process_block(blk)
        assert b.chain.head_root == a.chain.head_root

        net_a = Network(MINIMAL, a.chain, GossipHandlers(a.chain))
        net_b = Network(MINIMAL, b.chain, GossipHandlers(b.chain))
        net_c = Network(MINIMAL, c.chain, GossipHandlers(c.chain))
        port_a = await net_a.listen(0)
        port_b = await net_b.listen(0)
        peer_honest = await net_c.connect("127.0.0.1", port_a)
        peer_bad = await net_c.connect("127.0.0.1", port_b)

        # sabotage the BAD peer's serving side: blocks arrive corrupted
        orig = peer_bad.reqresp.blocks_by_range

        async def garbage(start, count, step=1):
            blocks = await orig(start, count, step)
            for blk in blocks:
                blk.message.state_root = b"\xde\xad" * 16  # breaks import
            return blocks

        peer_bad.reqresp.blocks_by_range = garbage
        # make the bad peer look strictly better so it is tried first
        peer_bad.status = Fields(
            fork_digest=peer_bad.status.fork_digest,
            finalized_root=peer_bad.status.finalized_root,
            finalized_epoch=peer_bad.status.finalized_epoch,
            head_root=peer_bad.status.head_root,
            head_slot=peer_bad.status.head_slot + 1,
        )

        reports = []

        async def report(peer, action, reason):
            reports.append((peer.peer_id, action))

        sync = RangeSync(MINIMAL, c.chain, net_c.peer_manager, report_peer=report)
        imported = await sync.run_to_head()
        assert imported > 0
        assert c.chain.head_root == a.chain.head_root
        assert any(pid == peer_bad.peer_id for pid, _ in reports), (
            "garbage peer was not downscored"
        )

        await net_c.close()
        await net_b.close()
        await net_a.close()
        pool_a.close()
        pool_b.close()
        pool_c.close()

    asyncio.run(main())
