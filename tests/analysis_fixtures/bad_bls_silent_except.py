"""Known-bad fixture: silent ``except`` arms on the BLS dispatch path.

Each marked handler swallows a failure without journaling, counting, or
re-raising — the invisibility class the chaos plane (lodestar_tpu/chaos)
exists to flush out: a lost device or failed compile that leaves NO
evidence anywhere.  Parsed by tests/test_static_analysis.py (scoped as a
``crypto/bls/`` path), never imported.
"""


def silent_swallows(verifier, packed, fut, logger, JOURNAL):
    try:
        out = verifier.dispatch(packed)
    except Exception:  # VIOLATION: lost dispatch, zero evidence
        out = None
    try:
        ok = out.result()
    except ValueError:  # VIOLATION: swallowed into a silent False verdict
        ok = False
    try:
        fut.set_result(ok)
    except RuntimeError:  # VIOLATION: assignment-only handler hides the drop
        ok = None
    return ok


def sanctioned_shapes(verifier, packed, fut, logger, JOURNAL, metrics):
    # journaling, counting, propagating, and re-raising are all sanctioned
    try:
        out = verifier.dispatch(packed)
    except Exception as e:
        JOURNAL.record("bls.degrade", error=str(e))
        raise
    try:
        ok = out.result()
    except ValueError as e:
        logger.warning("verdict failed: %s", e)  # WARNING+ mirrors to journal
        ok = False
    try:
        fut.set_result(ok)
    except RuntimeError as e:
        fut.set_exception(e)  # propagation onto the future is evidence
    try:
        verifier.pack(packed)
    except ValueError:
        verifier.pack_rejected += 1  # counting is evidence
    try:
        verifier.close()
    except OSError:
        metrics.bls_degrade_total.labels(where="close", tier="native").inc()
    return ok


def suppressed(out):
    try:
        return out.result()
    except Exception:  # lint: disable=bls-silent-except
        return None
