"""Known-bad sharded entry points for the jaxpr auditor's sharded rule
set (jaxpr-sharded-no-collective / jaxpr-sharded-local-final-exp).

IMPORTABLE, abstract-trace only (bad_jaxpr_programs discipline): the
bodies are TINY stand-ins that reproduce the structural signatures the
rules key on — a pow-x-window-length scan with an Fq12-shaped carry is
"a final exponentiation" to the auditor, so the fixtures stay cheap to
trace while proving detection live (the artifact disk cache is never
consulted for fixtures).
"""

import jax
import jax.numpy as jnp
from jax.experimental import shard_map as _shard_map
from jax.sharding import PartitionSpec as P

from lodestar_tpu.ops import limbs as fl
from lodestar_tpu.ops.pairing import _X_WINDOWS
from lodestar_tpu.ops.sharded_verify import MESH_AXIS


def _fake_final_exp(f):
    """The structural signature of one pow-by-x window scan: length
    len(_X_WINDOWS), (6, 2, NLIMBS) carry."""

    def body(c, w):
        return c * 1.0, None

    out, _ = jax.lax.scan(body, f, jnp.asarray(_X_WINDOWS))
    return out


def make_no_collective_entry(mesh):
    """A 'sharded' entry whose body never talks across shards: every
    chip sums only its local slice — the mesh verdict would be one
    shard's opinion."""

    def body(x):  # x: (local_n, 6, 2, NLIMBS)
        return (jnp.sum(x),)

    def fn(x):
        return _shard_map.shard_map(
            body, mesh=mesh, in_specs=(P(MESH_AXIS),), out_specs=(P(),),
            check_rep=False,
        )(x)[0]

    return fn


def make_local_final_exp_entry(mesh):
    """A sharded entry that runs the final exponentiation BEFORE the
    cross-shard combine — once per shard instead of once per merged
    batch (the serial-scan cost the sharded design exists to pay once)."""

    def body(x):  # x: (local_n, 6, 2, NLIMBS)
        f = jnp.sum(x, axis=0)  # local partial product stand-in
        f = _fake_final_exp(f)  # final exp on the LOCAL product: the bug
        g = jax.lax.all_gather(f, MESH_AXIS)
        return (jnp.sum(g),)

    def fn(x):
        return _shard_map.shard_map(
            body, mesh=mesh, in_specs=(P(MESH_AXIS),), out_specs=(P(),),
            check_rep=False,
        )(x)[0]

    return fn


def abstract_input(n: int):
    return jax.ShapeDtypeStruct((n, 6, 2, fl.NLIMBS), jnp.float32)
