"""Known-bad fixture: wall-clock timestamps feeding TRACER spans.

Spans recorded on different threads must share ONE monotonic clock;
``time.time()`` steps under NTP and breaks span ordering/merging.  Parsed
by tests/test_static_analysis.py, never imported.  The tracing-package
variant of the rule is exercised by linting THIS file again under a
pretend ``lodestar_tpu/tracing/`` path (where every ``time.time()`` call
fires, not just TRACER-nested ones).
"""

import time


def record_span(cid):
    TRACER.add_span("bls.pack", "bls", int(time.time() * 1e9), cid=cid)  # VIOLATION


def record_instant():
    TRACER.instant("clock.slot", ts=time.time())  # VIOLATION


def fine_outside_tracer():
    # wall clock for non-span purposes is allowed outside lodestar_tpu/tracing/
    started_at = time.time()  # PKG-VIOLATION: fires only under tracing/
    TRACER.add_span("ok.span", "ok", TRACER.now())
    return started_at
