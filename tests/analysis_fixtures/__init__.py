"""Known-bad fixtures for the static-analysis suite.

One fixture per lint rule; tests/test_static_analysis.py asserts each
checker fires EXACTLY on the lines marked ``# VIOLATION`` in its fixture
and nowhere in the live ``lodestar_tpu/`` tree.  The AST fixtures are
parsed, never imported (they reference undefined names on purpose);
``bad_jaxpr_programs`` is the importable exception — its programs are
traced by the jaxpr-auditor fixture tests.
"""

import os


def fixture_path(name: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)


def fixture_source(name: str) -> str:
    with open(fixture_path(name)) as f:
        return f.read()


def violation_lines(source: str) -> list:
    """1-based line numbers carrying a ``# VIOLATION`` marker."""
    return [
        i
        for i, line in enumerate(source.splitlines(), 1)
        if "# VIOLATION" in line
    ]
