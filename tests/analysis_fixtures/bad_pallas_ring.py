"""Known-bad fixture for ``pallas-ring-neighbor``: remote DMA device
ids derived from ``axis_index`` that are (1) not congruent mod the axis
size — the unwrapped ``my_id + 1`` that walks off the end of the mesh —
and (2) a self-send, the identity neighbor expression that deadlocks a
ring (nobody's receive ever completes)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental import shard_map
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "x"
N = 2


def _kernel(x_ref, o_ref, send, recv):
    me = lax.axis_index(AXIS)
    off_end = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=o_ref, send_sem=send, recv_sem=recv,
        device_id=me + 1,  # unwrapped: shard N-1 targets device N
        device_id_type=pltpu.DeviceIdType.MESH)
    off_end.start()  # VIOLATION pallas-ring-neighbor: not congruent mod N
    off_end.wait()
    narcissus = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=o_ref, send_sem=send, recv_sem=recv,
        device_id=me,  # identity: every shard sends to itself
        device_id_type=pltpu.DeviceIdType.MESH)
    narcissus.start()  # VIOLATION pallas-ring-neighbor: self-send
    narcissus.wait()


def build():
    def inner(x):
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
            interpret=True,
        )(x)

    mesh = Mesh(np.array(jax.devices()[:N]), (AXIS,))
    fn = shard_map.shard_map(
        inner, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS),
        check_rep=False)
    return fn, (jax.ShapeDtypeStruct((N * 8, 128), jnp.float32),)
