"""Known-bad fixture for ``pallas-dma-unbalanced``: a kernel whose DMA
semaphore ledger is broken both ways — a start whose wait never comes
(the count leaks across grid steps) and a wait whose start never
happened (deadlock at the first grid step).  Traced, never executed —
the interpret-mode discharge would hang on exactly these bugs, which is
the point of catching them statically."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref, sem_a, sem_b):
    leak = pltpu.make_async_copy(x_ref, o_ref, sem_a)
    leak.start()  # VIOLATION pallas-dma-unbalanced: no matching wait
    ghost = pltpu.make_async_copy(x_ref, o_ref, sem_b)
    ghost.wait()  # VIOLATION pallas-dma-unbalanced: wait without start


def build():
    """(fn, abstract args) for jax.make_jaxpr — the auditor fixture
    test extracts records from the traced graph."""

    def fn(x):
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((8, 128), jnp.float32),)
