"""Known-bad fixture for ``pallas-block-misaligned``: gridded block
shapes Mosaic rejects at compile time (the BENCH_r05 rc=124 class, one
layer down from the lax-level narrow-concat rule).  One call splits the
trailing (sublane, lane) dims into sub-tile pieces; the other picks a
block that does not divide the operand shape, leaving ragged edge
blocks.  Each ``pallas_call`` invocation sits on a single marked line —
the rule anchors violations at the call site."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _shape(x):
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def _subtile(x):
    # (5, 100) blocks: 5 < the f32 sublane tile 8, 100 % 128 != 0
    spec = pl.BlockSpec((5, 100), lambda i: (0, i))
    return pl.pallas_call(_copy_kernel, out_shape=_shape(x), grid=(3,), in_specs=[spec], out_specs=spec, interpret=True)(x)  # VIOLATION pallas-block-misaligned


def _ragged(x):
    # 7 does not divide 20: ragged edge blocks on the sublane dim
    spec = pl.BlockSpec((7, 128), lambda i: (0, i))
    return pl.pallas_call(_copy_kernel, out_shape=_shape(x), grid=(2,), in_specs=[spec], out_specs=spec, interpret=True)(x)  # VIOLATION pallas-block-misaligned


def build():
    def fn(a, b):
        return _subtile(a), _ragged(b)

    return fn, (
        jax.ShapeDtypeStruct((20, 300), jnp.float32),
        jax.ShapeDtypeStruct((20, 256), jnp.float32),
    )
