"""Known-bad fixture for ``jaxpr-limb-overflow`` (interval analysis).

IMPORTABLE like ``bad_jaxpr_programs``: tests trace these through
``limb_interval.analyze_callable`` (make_jaxpr only — no backend
compile) and assert the rule fires EXACTLY on the marked lines via the
jaxpr's per-eqn source info.

Each bad program respects the limb format's shapes but breaks a digit-
magnitude contract: the arithmetic stays silently *wrong* (f32 rounds
past 2^24), never raises — exactly the class of bug the fused pairing
kernels could only hit at batch scale on hardware.

``BAD_PROGRAMS`` / ``GOOD_PROGRAMS``: (fn, in_shapes, in_intervals).
"""

import jax.numpy as jnp

NLIMBS = 50
STRICT = (0.0, 256.0)  # semi-strict digit contract (carry fixed point)


def scaled_product_no_finalize(a, b):
    """Digit products of two strict elements are < 2^16 and exact — but
    re-scaling the product row by another full digit (a fused "shortcut"
    that skips the carry ladder) lands at 2^16 * 2^16 = 2^32, far past
    the 2^24 f32-exact ceiling: low bits are silently rounded away."""
    row = a * b  # fine: 256 * 256 = 2^16, exact
    scaled = row * 65025.0  # VIOLATION: 2^16 * 255^2 > 2^24, rounds
    return scaled * 0.0 + row


def lazy_add_ladder(x):
    """fp_add is deliberately lazy (digitwise sum, NO carry); chains must
    re-normalize before digits cross 2^24.  Doubling a strict element 17
    times without a single carry_exact crosses the ceiling."""
    acc = x
    for _ in range(17):
        acc = acc + acc  # VIOLATION: 2^8 << 17 = 2^25 > 2^24
    return acc


def anti_diagonal_over_accumulation(a, b):
    """The schoolbook multiply keeps anti-diagonal partial sums < 2^22 by
    folding every 50 rows; accumulating 50 rows of UN-shifted full-width
    products (a broken splice that drops the pad) concentrates all 50
    products (< 2^16 each) onto the same digits: 50 * 2^16 > 2^21 is
    still fine — so square the row first to model the digit-squared
    variant a transposed operand produces: 50 * 2^32 overflows."""
    z = jnp.zeros((NLIMBS,), dtype=jnp.float32)
    for i in range(NLIMBS):
        row = a * b
        z = z + row * row  # VIOLATION: sum of 50 digit-squared products
    return z


def carried_mac_chain(a, b):
    """GOOD: the same accumulation with the bound respected — products
    stay < 2^16 and the 50-term sum < 50 * 2^16 < 2^22, all exact."""
    z = jnp.zeros((NLIMBS,), dtype=jnp.float32)
    for _ in range(NLIMBS):
        z = z + a * b
    return z


def split_mod_idiom(d):
    """GOOD: the limbs._split carry idiom — interval analysis must
    recognize d - floor(d * 2^-8) * 2^8 as d mod 256 (naive interval
    subtraction would blow up the carry chain instead)."""
    hi = jnp.floor(d * (1.0 / 256.0))
    lo = d - hi * 256.0
    return lo + hi * 0.0


BAD_PROGRAMS = [
    (scaled_product_no_finalize, [(NLIMBS,), (NLIMBS,)], [STRICT, STRICT]),
    (lazy_add_ladder, [(NLIMBS,)], [STRICT]),
    (anti_diagonal_over_accumulation, [(NLIMBS,), (NLIMBS,)],
     [STRICT, STRICT]),
]

GOOD_PROGRAMS = [
    (carried_mac_chain, [(NLIMBS,), (NLIMBS,)], [STRICT, STRICT]),
    (split_mod_idiom, [(NLIMBS,)], [(0.0, float((1 << 24) - 1))]),
]
