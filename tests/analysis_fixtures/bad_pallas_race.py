"""Known-bad fixture for ``pallas-ref-race``: the double-buffer
slot-aliasing bug class.  A second DMA starts on the same semaphore
(slot) while the first is still in flight AND its destination slice
overlaps the first's — waits become ambiguous and the overlapping rows
land in nondeterministic order.  A second kernel half reads/writes a
ref slice a still-unwaited DMA is writing."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref, sem, sem2):
    first = pltpu.make_async_copy(x_ref, o_ref.at[pl.ds(0, 8)], sem)
    first.start()
    second = pltpu.make_async_copy(x_ref, o_ref.at[pl.ds(4, 8)], sem)
    second.start()  # VIOLATION pallas-ref-race: slot alias + overlapping write
    first.wait()
    second.wait()
    landing = pltpu.make_async_copy(x_ref, o_ref.at[pl.ds(8, 8)], sem2)
    landing.start()
    o_ref[8, 0] = o_ref[8, 0] + 1.0  # VIOLATION pallas-ref-race: in-flight slice
    landing.wait()


def build():
    def fn(x):
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((8, 128), jnp.float32),)
