"""Known-bad fixture: ``await`` while holding a threading lock.

A thread lock held across a suspension point blocks every other thread
needing that lock for the awaited duration — and deadlocks outright when
the awaited task itself needs the lock (the shape the PR-3 to_thread
workers make reachable).  Parsed by tests/test_static_analysis.py, never
imported.
"""

import asyncio


class Pool:
    async def flush_holding_lock(self):
        with self._sched_lock:
            verdict = await self.queue.get()  # VIOLATION
        return verdict

    async def sanctioned(self):
        # compute under the lock, await OUTSIDE it
        with self._sched_lock:
            batch = list(self._items)
        ok = await asyncio.to_thread(self.verifier.verify_signature_sets, batch)
        # asyncio locks are designed to be held across awaits
        async with self._aio_lock:
            await self.emit(ok)
        return ok
