"""Known-bad fixture for ``jaxpr-mxu-precision`` (dot precision contract).

IMPORTABLE like the other fixtures: tests trace these with
``jax.make_jaxpr`` (no backend compile), run
``jaxpr_audit.extract_artifacts`` + ``_check_mxu_precision`` on the
result, and assert the rule fires EXACTLY on the marked lines via the
dot census's per-eqn source info.

Each bad program is a structurally plausible limb contraction whose
``dot_general`` drops part of the MXU precision contract — the class of
dot XLA is free to evaluate through bf16 operands inside fusions,
silently rounding 16-bit digit products.  Nothing raises; the results
are bitwise plausible on small inputs and wrong at scale.

``BAD_PROGRAMS`` / ``GOOD_PROGRAMS``: (fn, in_shapes).  Every dot is
written on one source line so the eqn site lands on the marker.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

NLIMBS = 50

# module-level one-hot (constant-stability rule: long-lived, never a
# fresh temporary at trace time)
_ACC = np.eye(NLIMBS, dtype=np.float32)
_DN = (((1,), (0,)), ((), ()))


def bare_dot(x):
    """No precision, no preferred_element_type — the fully-naked dot a
    plain ``x @ W`` or ``jnp.dot`` produces."""
    return lax.dot_general(x, jnp.asarray(_ACC), _DN)  # VIOLATION


def preferred_only(x):
    """f32 accumulator pinned but operand precision left DEFAULT: XLA may
    still round the operands through bf16 before multiplying."""
    return lax.dot_general(x, jnp.asarray(_ACC), _DN, preferred_element_type=jnp.float32)  # VIOLATION


def highest_only(x):
    """HIGHEST operands but no explicit accumulator dtype: the contract
    requires both attributes, so exactness never depends on a backend
    default."""
    return lax.dot_general(x, jnp.asarray(_ACC), _DN, precision=lax.Precision.HIGHEST)  # VIOLATION


def half_highest(x):
    """A mixed (HIGHEST, DEFAULT) pair — one operand may still be
    downcast; the rule requires HIGHEST on BOTH sides."""
    return lax.dot_general(x, jnp.asarray(_ACC), _DN, precision=(lax.Precision.HIGHEST, lax.Precision.DEFAULT), preferred_element_type=jnp.float32)  # VIOLATION


def full_contract(x):
    """GOOD: the complete MXU precision contract, as limbs._dot_f32 and
    fused_core._m_dot emit it."""
    return lax.dot_general(
        x,
        jnp.asarray(_ACC),
        _DN,
        precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


BAD_PROGRAMS = [
    (bare_dot, [(4, NLIMBS)]),
    (preferred_only, [(4, NLIMBS)]),
    (highest_only, [(4, NLIMBS)]),
    (half_highest, [(4, NLIMBS)]),
]

GOOD_PROGRAMS = [
    (full_contract, [(4, NLIMBS)]),
]
