"""Known-bad fixture: blocking syncs lexically inside ``async def``.

Every marked line stalls the event loop for a whole device-dispatch
latency (the regression class PR 1's pipelined dispatch exists to avoid).
Parsed by tests/test_static_analysis.py, never imported.
"""

import asyncio
import time


async def drain_batch(pending, arr, y):
    ok = pending.result()  # VIOLATION: concurrent-future sync on the loop
    arr.block_until_ready()  # VIOLATION: device sync on the loop
    time.sleep(0.1)  # VIOLATION: wall-clock stall on the loop
    host = jax.device_get(y)  # VIOLATION: device->host readback on the loop
    return ok, host


async def sanctioned_shapes(pending, sets, verifier):
    # the sanctioned pattern: hand the BOUND METHOD to a worker thread
    ok = await asyncio.to_thread(pending.result)
    # plain awaits and non-blocking attribute access never trip the rule
    merged = await asyncio.to_thread(verifier.verify_signature_sets, sets)
    fut = asyncio.get_running_loop().create_future()
    fut.set_result(ok)  # set_result is not result()
    return merged


async def suppressed(pending):
    # inline opt-out for the rare justified case (docs/static_analysis.md)
    return pending.result()  # lint: disable=async-blocking-sync


def sync_context(pending):
    # outside async def the same calls are fine: result() IS the sync point
    return pending.result()
