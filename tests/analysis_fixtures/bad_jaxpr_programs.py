"""Known-bad fixture programs for the jaxpr auditor — one per IR rule.

IMPORTABLE (unlike the AST fixtures): tests trace these with
``jax.make_jaxpr`` and assert each rule fires.  Everything here is
abstract-trace only — nothing compiles or touches a device program, so
the conftest compile guard stays quiet.
"""

import jax
import jax.numpy as jnp


def stacked_18_lanes(x):
    """The pre-PR-1 ``lstack`` shape: jnp.stack over 18 operands chunks
    into concatenates of MIXED widths (16 + 2) whose concat-adjacent dims
    (2, 50) sit below the (8, 128) vreg tile — the exact splice Mosaic
    rejected in BENCH_r05 (rc=124)."""
    return jnp.stack([x[i] for i in range(18)], axis=0)


def f64_leak(x):
    """float64 escaping the sanctioned f32 limb format (only expressible
    under an x64 context — the test wraps the trace in
    jax.experimental.enable_x64)."""
    return x.astype(jnp.float64) * 2


def host_callback(x):
    """A host callback serialized into a hot-path program."""
    return jax.pure_callback(
        lambda a: a, jax.ShapeDtypeStruct(x.shape, x.dtype), x
    )


def make_captured_scalar_fn():
    """A device SCALAR captured by closure: the jit cache key (fn, avals)
    cannot see it, so a changed value silently reuses the stale program.
    Built lazily so importing this module materializes no device array."""
    captured = jnp.asarray(3.0)  # rank-0 device constant

    def f(x):
        return x * captured

    return f
