"""process_chain_segment cross-block batching (range-sync path).

Reference behavior: chain/blocks/index.ts processChainSegment imports a
contiguous segment; the reference's worker pool receives the whole
batch's signature sets at once (multithread/index.ts:153).  These tests
pin the round-5 semantics: one batched verification for the segment,
valid-prefix import when a block in the middle is bad, and idempotent
re-import.
"""

import asyncio

import pytest

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.chain.beacon_chain import BlockError
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier

from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL

CFG = ChainConfig(
    PRESET_BASE="minimal", SHARD_COMMITTEE_PERIOD=0, MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)


def _pool():
    v = FastBlsVerifier()
    return BlsBatchPool(v if v.native else FastBlsVerifier(), max_buffer_wait=0.005)


def _build_segment(n_slots: int):
    async def run():
        pool = _pool()
        producer = DevChain(MINIMAL, CFG, 16, pool)
        seg = []
        for slot in range(1, 1 + n_slots):
            root = await producer.advance_slot(slot)
            seg.append(producer.chain.get_block_by_root(root))
        pool.close()
        return seg

    return asyncio.run(run())


def test_segment_imports_in_one_batch():
    seg = _build_segment(6)

    async def run():
        pool = _pool()
        consumer = DevChain(MINIMAL, CFG, 16, pool)
        dispatches_before = getattr(pool, "dispatches", None)
        n = await consumer.chain.process_chain_segment(seg)
        pool.close()
        assert n == 6
        assert consumer.chain.head_root == consumer.chain.fork_choice.update_head()
        # idempotent re-import
        assert await consumer.chain.process_chain_segment(seg) == 0

    asyncio.run(run())


def test_segment_bad_block_imports_valid_prefix():
    seg = _build_segment(5)
    # corrupt block 3's proposer signature
    from lodestar_tpu.ssz import Fields

    bad = Fields(message=seg[3].message, signature=b"\xaa" * 96)
    tampered = seg[:3] + [bad] + seg[4:]

    async def run():
        pool = _pool()
        consumer = DevChain(MINIMAL, CFG, 16, pool)
        with pytest.raises(BlockError):
            await consumer.chain.process_chain_segment(tampered)
        # the valid prefix (blocks 0..2) must have imported
        for sb in seg[:3]:
            from lodestar_tpu.state_transition.upgrade import block_types

            root = block_types(MINIMAL, sb.message).BeaconBlock.hash_tree_root(
                sb.message
            )
            assert consumer.chain.fork_choice.has_block(root)
        pool.close()

    asyncio.run(run())


def test_segment_unknown_parent_raises():
    seg = _build_segment(4)

    async def run():
        pool = _pool()
        consumer = DevChain(MINIMAL, CFG, 16, pool)
        with pytest.raises(BlockError):
            await consumer.chain.process_chain_segment(seg[2:])
        pool.close()

    asyncio.run(run())
