"""Fork-schedule e2e: DevChain crosses phase0 -> altair -> bellatrix and
finalizes, with sync aggregates verified through the batch boundary.

Reference model: stateTransition.ts:100-144 fork dispatch +
slot/upgradeStateToAltair.ts / upgradeStateToBellatrix.ts; sim-test
precedent asserts finality against real components
(test/sim/multiNodeSingleThread.test.ts).
"""

import asyncio

import pytest

from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.config.fork_config import ForkName
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL
from lodestar_tpu.state_transition.upgrade import state_fork_name

CFG = ChainConfig(
    PRESET_BASE="minimal",
    SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_TIME=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=32,
    ALTAIR_FORK_EPOCH=1,
    BELLATRIX_FORK_EPOCH=2,
)
N_VALIDATORS = 32


def test_dev_chain_crosses_altair_and_bellatrix_and_finalizes():
    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, N_VALIDATORS, pool)

        # genesis era: phase0
        assert state_fork_name(dev.chain.head_state()) == ForkName.phase0

        # run 6 epochs: upgrade at epoch 1 (altair) and 2 (bellatrix),
        # then finalize on participation-flag justification
        await dev.run(6 * MINIMAL.SLOTS_PER_EPOCH + 2)

        state = dev.chain.head_state()
        assert state_fork_name(state) == ForkName.bellatrix
        assert bytes(state.fork.current_version) == CFG.BELLATRIX_FORK_VERSION
        assert bytes(state.fork.previous_version) == CFG.ALTAIR_FORK_VERSION
        # altair machinery is live
        assert len(state.current_sync_committee.pubkeys) == MINIMAL.SYNC_COMMITTEE_SIZE
        assert len(state.inactivity_scores) == N_VALIDATORS
        assert any(int(f) != 0 for f in state.previous_epoch_participation)
        # bellatrix pre-merge: payload header still default
        assert bytes(state.latest_execution_payload_header.block_hash) == b"\x00" * 32
        # finality across the fork boundary
        assert state.current_justified_checkpoint.epoch >= 4, "no justification"
        assert state.finalized_checkpoint.epoch >= 3, "no finalization"
        # sync aggregates carried real participation
        head_block = dev.chain.get_block_by_root(dev.chain.head_root).message
        bits = list(head_block.body.sync_aggregate.sync_committee_bits)
        assert any(bits), "sync aggregate has no participants"
        pool.close()

    asyncio.run(main())


def test_altair_upgrade_state_shape():
    """The upgraded state hashes/serializes under the altair schema."""

    async def main():
        pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, CFG, N_VALIDATORS, pool)
        await dev.run(MINIMAL.SLOTS_PER_EPOCH + 1)
        state = dev.chain.head_state()
        assert state_fork_name(state) == ForkName.altair
        assert not hasattr(state, "previous_epoch_attestations")
        from lodestar_tpu.state_transition.upgrade import state_types

        t = state_types(MINIMAL, state)
        blob = t.BeaconState.serialize(state)
        rt = t.BeaconState.deserialize(blob)
        assert t.BeaconState.hash_tree_root(rt) == t.BeaconState.hash_tree_root(state)
        pool.close()

    asyncio.run(main())
