"""Flight recorder & failure forensics (round 9): the always-on event
journal, the in-flight stall watchdog, diagnostic bundles, and the
bench stage-child salvage path.

Budget discipline (tests/conftest.py compile guard): every test here is
host-side — the fault-injection tests wedge a real ``TpuBlsVerifier``
whose per-executor device programs are stubs (the
tests/test_multidevice_scheduler.py pattern), so nothing is traced or
compiled by XLA.  The bench salvage test spawns a child that sleeps; it
imports jax but never touches a device program.
"""

import importlib.util
import json
import logging
import os
import signal
import time

import pytest

from lodestar_tpu import tracing
from lodestar_tpu.crypto.bls.api import interop_secret_key
from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet
from lodestar_tpu.forensics import (
    INFLIGHT,
    JOURNAL,
    RECORDER,
    latest_bundle,
    prune_bundles,
    write_bundle,
)
from lodestar_tpu.forensics.bundle import MANIFEST_NAME
from lodestar_tpu.forensics.journal import (
    REQUIRED_EVENT_KEYS,
    EventJournal,
    JournalHandler,
)
from lodestar_tpu.forensics.watchdog import InflightTable, Watchdog
from lodestar_tpu.metrics import create_metrics
from lodestar_tpu.tracing import TRACER
from lodestar_tpu.utils import logger as ulog

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


inspect_bundle = _load_tool("inspect_bundle")


@pytest.fixture(autouse=True)
def _clean_forensics():
    """The journal, in-flight table, tracer, and recorder are process
    singletons — scrub them around every test so forensics state never
    leaks across tests (or into other test modules)."""
    TRACER.disable()
    TRACER.clear()
    cap = JOURNAL.capacity
    JOURNAL.clear()
    JOURNAL.enabled = True
    INFLIGHT.clear()
    saved = (RECORDER._dir, RECORDER.metrics, RECORDER.pool, RECORDER.verifier)
    yield
    RECORDER.stop_watchdog()
    RECORDER.watchdog = None
    RECORDER._dir, RECORDER.metrics, RECORDER.pool, RECORDER.verifier = saved
    INFLIGHT.clear()
    JOURNAL.configure(capacity=cap)
    JOURNAL.clear()
    TRACER.disable()
    TRACER.clear()


def make_sets(n, start=0):
    out = []
    for i in range(start, start + n):
        sk = interop_secret_key(i % 16)
        msg = bytes([i % 256, i // 256 % 256]) * 16
        out.append(
            SingleSignatureSet(
                pubkey=sk.to_public_key(),
                signing_root=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


def stub_verifier(buckets=(4,)):
    """Real TpuBlsVerifier (real pack, real in-flight registration) whose
    device programs are host stubs — no XLA trace or compile."""
    v = TpuBlsVerifier(buckets=buckets, fused=False, host_final_exp=False)
    for ex in v._executors:
        for b in buckets:
            ex.compiled[(b, False, False)] = lambda *a: True
    return v


# ---------------------------------------------------------------------------
# event journal
# ---------------------------------------------------------------------------


class TestEventJournal:
    def test_ring_bounds_and_drop_counter(self):
        j = EventJournal(capacity=4)
        for i in range(7):
            j.record("tick", i=i)
        assert len(j) == 4
        assert j.dropped == 3  # silent eviction is counted, never hidden
        evs = j.events()
        assert [e["i"] for e in evs] == [3, 4, 5, 6]
        # seq strictly increasing and gapless across the ring
        assert [e["seq"] for e in evs] == [3, 4, 5, 6]
        assert j.tail(2) == evs[-2:]

    def test_event_schema_and_jsonl(self):
        j = EventJournal()
        j.record("pool.flush", sets=12, level="INFO")
        j.record("bad-level", level="NOT-A-LEVEL")
        lines = j.to_jsonl().strip().splitlines()
        assert len(lines) == 2
        for line in lines:
            ev = json.loads(line)
            for key in REQUIRED_EVENT_KEYS:
                assert key in ev, f"journal event missing {key!r}"
        assert json.loads(lines[1])["level"] == "INFO"  # unknown level coerced

    def test_cid_rides_the_tracing_contextvar(self):
        j = EventJournal()
        token = tracing.set_batch(77)
        try:
            j.record("bls.dispatch", device="cpu:0")
        finally:
            tracing.reset_batch(token)
        j.record("no-context")
        evs = j.events()
        assert evs[0]["cid"] == 77
        assert "cid" not in evs[1]

    def test_last_error_and_disabled_path(self):
        j = EventJournal()
        assert j.last_error() is None
        j.record("a", level="WARNING")
        j.record("b", level="ERROR", what="first")
        j.record("c", level="CRITICAL", what="second")
        assert j.last_error()["what"] == "second"
        j.enabled = False
        j.record("d", level="ERROR")
        assert len(j) == 3

    def test_log_handler_bridges_warnings(self):
        j = EventJournal()
        h = JournalHandler(j)
        lg = logging.getLogger("lodestar.test_forensics_bridge")
        lg.addHandler(h)
        lg.propagate = False
        try:
            lg.info("quiet")  # below the handler threshold
            lg.warning("loud %d", 42)
        finally:
            lg.removeHandler(h)
        evs = j.events()
        assert len(evs) == 1
        assert evs[0]["kind"] == "log"
        assert evs[0]["level"] == "WARNING"
        assert evs[0]["msg"] == "loud 42"
        assert evs[0]["logger"] == "lodestar.test_forensics_bridge"


# ---------------------------------------------------------------------------
# logger: duplicate-handler guard + json mode (satellite 2)
# ---------------------------------------------------------------------------


class TestLoggerForensics:
    def test_reconfigure_never_stacks_stderr_handlers(self):
        """Regression: a spawn child re-importing the package (or a test
        harness resetting ``_configured``) must not add a second stream
        handler — before the guard every line double-emitted."""
        root = ulog._configure_root()

        def count(role):
            return sum(
                1 for h in root.handlers
                if getattr(h, ulog._HANDLER_TAG, None) == role
            )

        assert count("stream") == 1
        was_configured = ulog._configured
        try:
            ulog._configured = False  # the spawn-child re-import shape
            ulog._configure_root()
            ulog.get_logger("again")
        finally:
            ulog._configured = was_configured
        assert count("stream") == 1, "re-configure stacked a stderr handler"
        assert count("journal") == 1, "re-configure stacked a journal handler"

    def test_journal_handler_attached_to_root(self):
        root = ulog._configure_root()
        tagged = [
            h for h in root.handlers
            if getattr(h, ulog._HANDLER_TAG, None) == "journal"
        ]
        assert len(tagged) == 1 and isinstance(tagged[0], JournalHandler)
        before = len(JOURNAL)
        ulog.get_logger("forensics_attach").warning("black box me")
        evs = JOURNAL.events()[before:]
        assert any(e.get("msg") == "black box me" for e in evs)

    def test_json_format_mode(self):
        h = ulog._tagged_handler(ulog._configure_root(), "stream")
        assert h is not None
        try:
            ulog.set_format("json")
            rec = logging.LogRecord(
                "lodestar.x", logging.WARNING, __file__, 1, "boom %d", (7,), None
            )
            rec.cid = 5
            out = json.loads(h.formatter.format(rec))
            assert out["level"] == "WARNING"
            assert out["logger"] == "lodestar.x"
            assert out["msg"] == "boom 7"
            assert out["cid"] == 5
            assert isinstance(out["ts"], float)
        finally:
            ulog.set_format("text")
        with pytest.raises(ValueError):
            ulog.set_format("xml")

    def test_cid_filter_stamps_records(self):
        h = ulog._tagged_handler(ulog._configure_root(), "stream")
        token = tracing.set_batch(31)
        try:
            rec = logging.LogRecord(
                "lodestar.x", logging.INFO, __file__, 1, "hi", (), None
            )
            for f in h.filters:
                f.filter(rec)
        finally:
            tracing.reset_batch(token)
        assert rec.cid == 31


# ---------------------------------------------------------------------------
# in-flight table + watchdog
# ---------------------------------------------------------------------------


class TestInflightTable:
    def test_register_resolve_snapshot(self):
        t = InflightTable()
        tok = t.register(cid=5, device="cpu:0", bucket=4, sets=3)
        assert len(t) == 1
        snap = t.snapshot()
        assert snap[0]["cid"] == 5 and snap[0]["device"] == "cpu:0"
        assert snap[0]["age_s"] >= 0
        t.resolve(tok)
        assert len(t) == 0
        t.resolve(tok)  # idempotent

    def test_flag_stalled_fires_once_per_entry(self):
        t = InflightTable()
        t.register(cid=1, device="cpu:0")
        now = time.monotonic_ns()
        late = now + int(10e9)
        assert t.flag_stalled(30.0, now_ns=now) == []
        first = t.flag_stalled(5.0, now_ns=late)
        assert [e["cid"] for e in first] == [1]
        # one wedge -> one stall event, not one per scan
        assert t.flag_stalled(5.0, now_ns=late) == []
        # the entry stays visible (and marked) until resolved
        assert t.snapshot()[0]["stalled"] is True


class TestWatchdog:
    def test_check_once_journals_counts_and_dumps(self):
        t = InflightTable()
        j = EventJournal()
        m = create_metrics()
        dumps = []
        wd = Watchdog(deadline_s=0.01, interval_s=10.0, inflight=t, journal=j,
                      metrics=m, on_stall=dumps.append)
        t.register(cid=9, device="cpu:1", bucket=4, sets=2)
        time.sleep(0.03)
        stalled = wd.check_once()
        assert [e["cid"] for e in stalled] == [9]
        assert wd.stalls == 1
        ev = j.last_error()
        assert ev["kind"] == "watchdog.stall"
        assert ev["cid"] == 9 and ev["device"] == "cpu:1"
        assert len(dumps) == 1 and dumps[0][0]["cid"] == 9
        text = m.reg.expose().decode()
        assert 'lodestar_bls_watchdog_stalls_total{device="cpu:1"} 1.0' in text
        # the same wedge never re-fires
        assert wd.check_once() == []
        assert wd.stalls == 1

    def test_dump_hook_failure_never_kills_the_scan(self):
        t = InflightTable()
        wd = Watchdog(deadline_s=0.0, interval_s=10.0, inflight=t,
                      journal=EventJournal(),
                      on_stall=lambda e: (_ for _ in ()).throw(OSError("disk")))
        t.register(cid=1, device="cpu:0")
        time.sleep(0.01)
        assert len(wd.check_once()) == 1  # no exception escaped


# ---------------------------------------------------------------------------
# diagnostic bundles + tools/inspect_bundle.py (satellite 4)
# ---------------------------------------------------------------------------


class TestBundleRoundTrip:
    def _populate(self):
        JOURNAL.record("jax.compile", event="backend_compile", seconds=2.5)
        JOURNAL.record("bls.dispatch", cid=3, device="cpu:0", bucket=4, sets=2)
        ulog.get_logger("forensics_rt").warning("pre-crash warning")
        ulog.get_logger("forensics_rt").error("pre-crash error")
        TRACER.enable()
        TRACER.add_span("bls.pack", "bls", 0, 1_000_000, cid=3)

    def test_write_validate_summarize(self, tmp_path):
        self._populate()
        tok = INFLIGHT.register(cid=3, device="cpu:0", bucket=4, sets=2)
        INFLIGHT.flag_stalled(0.0)
        path = write_bundle(str(tmp_path), "unit test!")
        assert os.path.basename(path).startswith("bundle-unit-test-")
        assert inspect_bundle.validate(path) == [], "bundle failed its own schema"
        s = inspect_bundle.summarize(path)
        assert s["reason"] == "unit test!"
        assert s["last_compile"]["seconds"] == 2.5
        assert s["stalled"][0]["cid"] == 3
        assert s["stalled"][0]["device"] == "cpu:0"
        assert s["inflight_per_device"] == {"cpu:0": 1}
        assert s["journal_dropped"] == 0 and s["trace_dropped"] == 0
        assert any(e.get("msg") == "pre-crash error" for e in s["last_errors"])
        assert any(e.get("msg") == "pre-crash warning" for e in s["last_warnings"])
        INFLIGHT.resolve(tok)

    def test_cli_text_and_json(self, tmp_path, capsys):
        self._populate()
        path = write_bundle(str(tmp_path), "cli")
        assert inspect_bundle.main([path]) == 0
        assert "reason   cli" in capsys.readouterr().out
        assert inspect_bundle.main([path, "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["reason"] == "cli"

    def test_corrupt_bundles_fail_validation(self, tmp_path):
        path = write_bundle(str(tmp_path), "corrupt")
        # a listed-but-missing file means corruption (manifest is last)
        os.unlink(os.path.join(path, "journal.jsonl"))
        errs = inspect_bundle.validate(path)
        assert any("journal.jsonl" in e and "absent" in e for e in errs)
        assert inspect_bundle.main([path]) == 1
        # a manifest that cannot say its drop counts is rejected
        mpath = os.path.join(path, MANIFEST_NAME)
        manifest = json.load(open(mpath))
        del manifest["journal"]["dropped"]
        json.dump(manifest, open(mpath, "w"))
        errs = inspect_bundle.validate(path)
        assert any("journal.dropped" in e for e in errs)
        # no manifest at all -> bundle incomplete
        os.unlink(mpath)
        errs = inspect_bundle.validate(path)
        assert len(errs) == 1 and "incomplete or corrupt" in errs[0]

    def test_prune_and_latest(self, tmp_path):
        paths = [write_bundle(str(tmp_path), f"b{i}") for i in range(4)]
        for p in paths:
            now = time.time()
            os.utime(os.path.join(p, MANIFEST_NAME), (now, now + paths.index(p)))
            os.utime(p, (now, now + paths.index(p)))
        # a manifest-less directory is never "latest" (incomplete dump)
        incomplete = os.path.join(str(tmp_path), "bundle-partial-1-99")
        os.makedirs(incomplete)
        assert latest_bundle(str(tmp_path)) == paths[-1]
        prune_bundles(str(tmp_path), keep=2)
        left = sorted(n for n in os.listdir(str(tmp_path)) if n.startswith("bundle-"))
        # 2 newest kept; older bundles AND the incomplete husk are swept
        assert left == sorted(os.path.basename(p) for p in paths[-2:])

    def test_per_section_failures_land_in_manifest(self, tmp_path):
        class BrokenRegistry:
            def expose(self):
                raise RuntimeError("exposition exploded")

        path = write_bundle(str(tmp_path), "partial",
                            metrics_registry=BrokenRegistry())
        manifest = json.load(open(os.path.join(path, MANIFEST_NAME)))
        assert "metrics.prom" in manifest["errors"]
        assert "metrics.prom" not in manifest["files"]
        # partial evidence still validates (the failure is recorded)
        assert inspect_bundle.validate(path) == []


# ---------------------------------------------------------------------------
# fault injection: a wedged dispatch becomes a metric + a named bundle
# ---------------------------------------------------------------------------


class TestWedgedDispatch:
    def test_watchdog_writes_bundle_naming_cid_and_device(self, tmp_path):
        """Acceptance: a wedged in-flight batch triggers
        ``bls_watchdog_stalls_total`` and an automatic bundle naming the
        stalled cid and device within one watchdog period."""
        v = stub_verifier()
        m = create_metrics()
        RECORDER.configure(forensics_dir=str(tmp_path), metrics=m, verifier=v)
        token = tracing.set_batch(1234)
        try:
            pend = v.dispatch(v.pack(make_sets(2)))
        finally:
            tracing.reset_batch(token)
        assert len(INFLIGHT) == 1

        RECORDER.start_watchdog(deadline_s=0.15, interval_s=0.05)
        deadline = time.monotonic() + 5.0
        bundle = None
        while time.monotonic() < deadline:
            bundle = latest_bundle(str(tmp_path))
            if bundle:
                break
            time.sleep(0.02)
        assert bundle, "watchdog never dumped a bundle for the wedged batch"

        assert inspect_bundle.validate(bundle) == []
        s = inspect_bundle.summarize(bundle)
        assert s["reason"] == "watchdog"
        assert s["stalled"], "bundle does not name any stalled batch"
        assert s["stalled"][0]["cid"] == 1234
        assert s["stalled"][0]["device"] == pend.device
        assert s["verifier"]["type"] == "TpuBlsVerifier"
        text = m.reg.expose().decode()
        assert (
            f'lodestar_bls_watchdog_stalls_total{{device="{pend.device}"}} 1.0'
            in text
        )
        # the stall is in the journal (and therefore in the bundle tail)
        ev = JOURNAL.last_error()
        assert ev["kind"] == "watchdog.stall" and ev["cid"] == 1234
        # resolving the verdict clears the table; no second bundle fires
        RECORDER.stop_watchdog()
        assert pend.result() is True
        assert len(INFLIGHT) == 0

    def test_dispatch_resolve_keeps_table_empty(self):
        v = stub_verifier()
        pends = [v.dispatch(v.pack(make_sets(1, start=i))) for i in range(3)]
        assert len(INFLIGHT) == 3
        snap = INFLIGHT.snapshot()
        assert all(e["device"] for e in snap)
        for p in pends:
            assert p.result() is True
            assert p.result() is True  # idempotent result -> single resolve
        assert len(INFLIGHT) == 0


# ---------------------------------------------------------------------------
# signal-triggered dumps (satellite 4: SIGUSR2)
# ---------------------------------------------------------------------------


class TestSignalDump:
    def test_sigusr2_dumps_and_continues(self, tmp_path):
        RECORDER.configure(forensics_dir=str(tmp_path))
        JOURNAL.record("pre-signal", marker="xyz")
        RECORDER.install_signal_handlers(signals=(signal.SIGUSR2,))
        try:
            os.kill(os.getpid(), signal.SIGUSR2)
            deadline = time.monotonic() + 5.0
            bundle = None
            while time.monotonic() < deadline:
                bundle = latest_bundle(str(tmp_path))
                if bundle:
                    break
                time.sleep(0.01)
        finally:
            RECORDER.uninstall_signal_handlers()
        assert bundle, "SIGUSR2 did not produce a bundle"
        assert "sigusr2" in os.path.basename(bundle)
        assert inspect_bundle.validate(bundle) == []
        events = [json.loads(l) for l in open(os.path.join(bundle, "journal.jsonl"))]
        assert any(e.get("marker") == "xyz" for e in events)
        # and the process carried on (we are still here)
        assert signal.getsignal(signal.SIGUSR2) in (
            signal.SIG_DFL, signal.SIG_IGN, signal.default_int_handler,
        ) or callable(signal.getsignal(signal.SIGUSR2))

    def test_sig_ign_disposition_survives_the_hook(self, tmp_path):
        """A signal the process previously IGNORED must still be survived
        after the recorder hooks it — the dump is evidence, not a new
        death sentence (SIGUSR1 stands in for a supervisor's SIG_IGN
        SIGTERM; actually raising SIGTERM would kill pytest)."""
        RECORDER.configure(forensics_dir=str(tmp_path))
        prev = signal.signal(signal.SIGUSR1, signal.SIG_IGN)
        try:
            RECORDER.install_signal_handlers(signals=(signal.SIGUSR1,))
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not latest_bundle(str(tmp_path)):
                time.sleep(0.01)
        finally:
            RECORDER.uninstall_signal_handlers()
            signal.signal(signal.SIGUSR1, prev)
        bundle = latest_bundle(str(tmp_path))
        assert bundle and "sigusr1" in os.path.basename(bundle)
        # still alive: the SIG_IGN survival semantic was preserved


# ---------------------------------------------------------------------------
# drop-counter metrics (satellite 3)
# ---------------------------------------------------------------------------


class TestDropVisibility:
    def test_publish_metrics_surfaces_ring_evictions(self):
        m = create_metrics()
        RECORDER.configure(metrics=m)
        JOURNAL.configure(capacity=2)
        for i in range(5):
            JOURNAL.record("tick", i=i)
        tracing.enable(capacity=2)
        for i in range(4):
            TRACER.add_span("bls.pack", "bls", 0, 10, cid=i)
        RECORDER.publish_metrics()
        text = m.reg.expose().decode()
        assert "lodestar_forensics_journal_dropped_total 3.0" in text
        assert "lodestar_tracing_spans_dropped_total 2.0" in text


# ---------------------------------------------------------------------------
# REST: spec health + aggregated health + on-demand forensics
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self, slot):
        self.current_slot = slot


class _FakeState:
    def __init__(self, slot):
        self.slot = slot


class _FakeChain:
    def __init__(self, head_slot, clock_slot):
        self._head = _FakeState(head_slot)
        self.clock = _FakeClock(clock_slot)
        self.bls = None

    def head_state(self):
        return self._head


class TestRestForensics:
    def _server(self, chain):
        from lodestar_tpu.api.rest import RestApiServer
        from lodestar_tpu.params import MINIMAL

        return RestApiServer(MINIMAL, chain)

    def test_node_health_semantics(self):
        """Satellite 1: 200 ready, 206 syncing, 503 not ready — the
        status code IS the answer (routes/node.ts getHealth)."""
        import asyncio

        async def main():
            ready = self._server(_FakeChain(head_slot=10, clock_slot=10))
            status, _, _ = await ready._dispatch("GET", "/eth/v1/node/health", b"")
            assert status == 200
            syncing = self._server(_FakeChain(head_slot=4, clock_slot=32))
            status, _, _ = await syncing._dispatch("GET", "/eth/v1/node/health", b"")
            assert status == 206
            dead = self._server(chain=None)
            status, _, _ = await dead._dispatch("GET", "/eth/v1/node/health", b"")
            assert status == 503

        asyncio.run(main())

    def test_lodestar_health_aggregates(self):
        import asyncio

        async def main():
            server = self._server(_FakeChain(head_slot=10, clock_slot=10))
            tok = INFLIGHT.register(cid=8, device="cpu:0", bucket=4, sets=1)
            ulog.get_logger("forensics_health").error("recent failure")
            status, payload, _ = await server._dispatch(
                "GET", "/eth/v1/lodestar/health", b""
            )
            INFLIGHT.resolve(tok)
            assert status == 200
            data = payload["data"]
            assert data["status"] == 200
            assert data["inflight"][0]["cid"] == 8
            assert data["journal"]["last_error"]["msg"] == "recent failure"
            assert data["journal"]["events"] >= 1
            # the aggregate inherits the spec health status code
            sick = self._server(chain=None)
            status, payload, _ = await sick._dispatch(
                "GET", "/eth/v1/lodestar/health", b""
            )
            assert status == 503 and payload["data"]["status"] == 503

        asyncio.run(main())

    def test_forensics_endpoint_writes_bundle(self, tmp_path):
        import asyncio

        async def main():
            m = create_metrics()
            RECORDER.configure(forensics_dir=str(tmp_path), metrics=m)
            server = self._server(_FakeChain(head_slot=1, clock_slot=1))
            status, payload, _ = await server._dispatch(
                "GET", "/eth/v1/lodestar/forensics?reason=drill", b""
            )
            assert status == 200
            data = payload["data"]
            assert data["manifest"]["reason"] == "api-drill"
            assert os.path.isdir(data["bundle"])
            assert inspect_bundle.validate(data["bundle"]) == []
            # caller text is slugged out of the path and NEVER the metric
            # label — query strings must not mint label cardinality
            status, payload, _ = await server._dispatch(
                "GET", "/eth/v1/lodestar/forensics?reason=../../../etc%20evil",
                b"",
            )
            assert status == 200
            assert "/etc" not in payload["data"]["manifest"]["reason"]
            text = m.reg.expose().decode()
            assert 'lodestar_forensics_bundles_written_total{reason="api"} 2.0' in text
            assert "drill" not in text

        asyncio.run(main())

    def test_dump_prunes_its_own_dir(self, tmp_path):
        RECORDER.configure(forensics_dir=str(tmp_path))
        keep, RECORDER.keep_bundles = RECORDER.keep_bundles, 3
        try:
            for i in range(6):
                RECORDER.dump(f"poll{i}")
        finally:
            RECORDER.keep_bundles = keep
        left = [n for n in os.listdir(str(tmp_path)) if n.startswith("bundle-")]
        assert len(left) == 3  # repeated triggers cannot fill the disk


# ---------------------------------------------------------------------------
# bench salvage: a timed-out stage child leaves a diagnosable artifact
# ---------------------------------------------------------------------------


class TestBenchSalvage:
    def test_stage_timeout_attaches_salvage_bundle(self, tmp_path, monkeypatch):
        """Acceptance: killing a bench stage child via the existing
        ``BENCH_STAGE_TIMEOUT_S`` path yields a bundle path in the stage
        error that ``tools/inspect_bundle.py`` validates and summarizes —
        the next rc=124 is a diagnosable artifact, not a wall-clock
        number.  The child only sleeps (``bench_wedge``); no device
        program is built on either side."""
        import bench
        from lodestar_tpu.forensics import salvage

        monkeypatch.setenv(salvage.BASE_DIR_ENV, str(tmp_path))
        monkeypatch.setenv(salvage.INTERVAL_ENV, "0.2")
        # generous enough for the spawn child to finish importing jax +
        # bench and write its first heartbeat; tiny next to a real stage
        monkeypatch.setenv("BENCH_STAGE_TIMEOUT_S", "30")

        out, err = bench._stage("bench_wedge", (3600.0,), retries=0)
        assert out is None
        assert isinstance(err, dict)
        assert err["error"].startswith("timeout after")
        bundle = err["bundle"]
        assert bundle, "timeout carried no salvage bundle"
        assert bundle.startswith(str(tmp_path))

        assert inspect_bundle.validate(bundle) == []
        s = inspect_bundle.summarize(bundle)
        assert s["reason"] == "heartbeat"
        # the child journaled its own stage start before wedging
        events = [json.loads(l) for l in open(os.path.join(bundle, "journal.jsonl"))]
        starts = [e for e in events if e.get("kind") == "bench.stage_start"]
        assert starts and starts[0]["stage"] == "bench_wedge"
        assert starts[0]["pid"] != os.getpid()

    def test_latest_stage_bundle_scoping(self, tmp_path, monkeypatch):
        from lodestar_tpu.forensics import salvage

        monkeypatch.setenv(salvage.BASE_DIR_ENV, str(tmp_path))
        assert salvage.latest_stage_bundle("never_ran") is None
        hb = salvage.Heartbeat("unit_stage", interval_s=60.0)
        path = hb.beat()
        assert path and salvage.latest_stage_bundle("unit_stage") == path
        # heartbeats prune themselves to the newest few
        for _ in range(salvage.KEEP_BUNDLES + 2):
            path = hb.beat()
        kept = [n for n in os.listdir(salvage.stage_dir("unit_stage"))
                if n.startswith("bundle-")]
        assert len(kept) <= salvage.KEEP_BUNDLES
        assert salvage.latest_stage_bundle("unit_stage") == path
        # pid scoping: a previous run's bundle is never attributed to a
        # child (by pid) that died before its first heartbeat
        assert salvage.latest_stage_bundle("unit_stage", pid=os.getpid()) == path
        assert salvage.latest_stage_bundle("unit_stage", pid=999999999) is None
