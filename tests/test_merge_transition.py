"""Merge-transition e2e: the dev chain crosses into bellatrix against
ExecutionEngineMock, payloads flow through notify_new_payload on import and
engine_forkchoiceUpdated on head change, and an EL-invalidated payload
reorgs out of the canonical chain.

Reference flow: verifyBlock.ts:195-263 (newPayload + optimistic gating),
importBlock.ts:251-280 (forkchoiceUpdated), forkChoice.ts validateLatestHash
(invalidation).  VERDICT r3 item 5.
"""

import asyncio

import pytest

from lodestar_tpu.chain.beacon_chain import BeaconChain, BlockError
from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.config.chain_config import ChainConfig
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier
from lodestar_tpu.execution.engine import ExecutePayloadStatus, ExecutionEngineMock
from lodestar_tpu.node.dev_chain import DevChain
from lodestar_tpu.params import MINIMAL


def _cfg() -> ChainConfig:
    # phase0 genesis -> altair at epoch 1 (slot 8) -> bellatrix at epoch 2
    # (slot 16, minimal preset)
    return ChainConfig(
        PRESET_BASE="minimal",
        MIN_GENESIS_TIME=0,
        SHARD_COMMITTEE_PERIOD=0,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
        ALTAIR_FORK_EPOCH=1,
        BELLATRIX_FORK_EPOCH=2,
    )


def _dev(engine) -> DevChain:
    pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.001)
    return DevChain(MINIMAL, _cfg(), 16, pool, execution_engine=engine)


def test_merge_transition_e2e():
    engine = ExecutionEngineMock(MINIMAL, genesis_block_hash=b"\x11" * 32)
    dev = _dev(engine)

    async def run():
        # bellatrix activates at slot 16; run past it
        for slot in range(1, 20):
            await dev.advance_slot(slot)
        return dev.chain.head_state()

    state = asyncio.run(run())
    # the chain crossed the merge: the state carries a real payload header
    assert bytes(state.latest_execution_payload_header.block_hash) != b"\x00" * 32
    # head node is fully verified (mock returns VALID) and carries the hash
    head = dev.chain.fork_choice.get_block(dev.chain.head_root)
    assert head.execution_status == "valid"
    assert head.execution_block_hash == bytes(
        state.latest_execution_payload_header.block_hash
    )
    # the engine followed the head via forkchoiceUpdated
    assert engine.head_block_hash == head.execution_block_hash


def test_invalid_payload_rejected_on_import():
    engine = ExecutionEngineMock(MINIMAL, genesis_block_hash=b"\x11" * 32)
    dev = _dev(engine)

    async def run():
        for slot in range(1, 18):
            await dev.advance_slot(slot)
        # next produced block's payload is reported INVALID by the engine
        real_npl = engine.notify_new_payload
        engine.notify_new_payload = lambda p: ExecutePayloadStatus.INVALID
        blk = None
        try:
            with pytest.raises(BlockError, match="INVALID"):
                await dev.advance_slot(18)
        finally:
            engine.notify_new_payload = real_npl
        return dev.chain.head_state()

    state = asyncio.run(run())
    assert state.slot <= 18


def test_optimistic_import_then_el_invalidation_reorgs():
    engine = ExecutionEngineMock(MINIMAL, genesis_block_hash=b"\x11" * 32)
    dev = _dev(engine)

    async def run():
        for slot in range(1, 18):
            await dev.advance_slot(slot)
        head_before = dev.chain.head_root
        # the EL is syncing: block 18 imports optimistically
        real_npl = engine.notify_new_payload
        engine.notify_new_payload = lambda p: ExecutePayloadStatus.SYNCING
        root10 = await dev.advance_slot(18, with_attestations=False)
        engine.notify_new_payload = real_npl
        node = dev.chain.fork_choice.get_block(root10)
        assert node.execution_status == "syncing"
        assert dev.chain.head_root == root10
        # the EL finishes syncing and reports the payload INVALID
        await dev.chain.on_invalid_execution_payload(root10)
        assert dev.chain.fork_choice.get_block(root10).execution_status == "invalid"
        # head reorged off the invalid block
        assert dev.chain.head_root == head_before
        return True

    assert asyncio.run(run())


def test_merge_transition_block_cannot_import_optimistically():
    engine = ExecutionEngineMock(MINIMAL, genesis_block_hash=b"\x11" * 32)
    dev = _dev(engine)

    async def run():
        for slot in range(1, 16):  # phase0 + altair epochs
            await dev.advance_slot(slot)
        # slot 16 = first bellatrix block = merge-transition block; a
        # SYNCING verdict must reject it (verifyBlock.ts:219-263)
        engine.notify_new_payload = lambda p: ExecutePayloadStatus.SYNCING
        with pytest.raises(BlockError, match="optimistically"):
            await dev.advance_slot(16)
        return True

    assert asyncio.run(run())
