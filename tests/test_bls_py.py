"""Ground-truth BLS12-381 tests.

Validation strategy (no spec-test vectors available offline):
1. Algebraic identities: generator orders, bilinearity, non-degeneracy.
2. Differential fixture: interop pubkeys vs the reference repo's cached
   interop-pubkeys.json (real @chainsafe/blst output) — pins down Fq
   arithmetic, G1 scalar mult, and ZCash compression bit-exactly.
3. Round trips and negative cases for every API.
"""

import json
import os

import pytest

from lodestar_tpu.crypto.bls import (
    PublicKey,
    SecretKey,
    Signature,
    aggregate_signatures,
    aggregate_verify,
    fast_aggregate_verify,
    interop_pubkeys,
    interop_secret_key,
    verify,
    verify_multiple_signatures,
    PyBlsVerifier,
    SingleSignatureSet,
    AggregatedSignatureSet,
)
from lodestar_tpu.crypto.bls.curve import (
    G1_GEN,
    G2_GEN,
    g1_from_bytes,
    g1_subgroup_check,
    g1_to_bytes,
    g2_from_bytes,
    g2_subgroup_check,
    g2_to_bytes,
    psi,
    Point,
    B1,
    B2,
)
from lodestar_tpu.crypto.bls.fields import BLS_X, Fq2, Fq12, P, R
from lodestar_tpu.crypto.bls.hash_to_curve import expand_message_xmd, hash_to_g2
from lodestar_tpu.crypto.bls.pairing import pairing, multi_pairing

INTEROP_PUBKEYS_PATH = "/root/reference/packages/state-transition/test-cache/interop-pubkeys.json"

MSG = b"\xab" * 32


class TestFields:
    def test_fq2_inverse(self):
        a = Fq2(123456789, 987654321)
        assert a * a.inv() == Fq2.one()

    def test_fq2_sqrt_roundtrip(self):
        a = Fq2(1234, 5678)
        sq = a.square()
        root = sq.sqrt()
        assert root is not None
        assert root.square() == sq

    def test_fq2_frobenius_is_pth_power(self):
        a = Fq2(31415, 92653)
        assert a.frobenius() == a.pow(P)

    def test_fq12_inverse(self):
        from lodestar_tpu.crypto.bls.fields import Fq6

        x = Fq12(
            Fq6(Fq2(1, 2), Fq2(3, 4), Fq2(5, 6)),
            Fq6(Fq2(7, 8), Fq2(9, 10), Fq2(11, 12)),
        )
        assert x * x.inv() == Fq12.one()

    def test_fq12_frobenius_is_pth_power(self):
        from lodestar_tpu.crypto.bls.fields import Fq6

        x = Fq12(
            Fq6(Fq2(1, 2), Fq2(3, 4), Fq2(5, 6)),
            Fq6(Fq2(7, 8), Fq2(9, 10), Fq2(11, 12)),
        )
        assert x.frobenius() == x.pow(P)


class TestCurve:
    def test_generators(self):
        assert G1_GEN.is_on_curve()
        assert G2_GEN.is_on_curve()
        assert (G1_GEN * R).is_infinity()
        assert (G2_GEN * R).is_infinity()

    def test_subgroup_checks(self):
        assert g1_subgroup_check(G1_GEN)
        assert g2_subgroup_check(G2_GEN)
        assert g1_subgroup_check(G1_GEN * 7)
        assert g2_subgroup_check(G2_GEN * 7)

    def test_psi_eigenvalue(self):
        # psi acts as multiplication by z on G2
        q = G2_GEN * 987654321
        assert psi(q) == q * BLS_X

    def test_g2_point_not_in_subgroup_detected(self):
        # find a curve point NOT in G2 (E2 has large cofactor, so a random
        # curve point is essentially never in the subgroup)
        x = Fq2(1, 1)
        while True:
            y2 = x.square() * x + B2
            y = y2.sqrt()
            if y is not None:
                pt = Point.from_affine(x, y, B2)
                break
            x = x + Fq2.one()
        assert pt.is_on_curve()
        assert not g2_subgroup_check(pt)

    def test_serialization_roundtrip(self):
        for k in (1, 2, 0xDEADBEEF):
            p1 = G1_GEN * k
            assert g1_from_bytes(g1_to_bytes(p1)) == p1
            p2 = G2_GEN * k
            assert g2_from_bytes(g2_to_bytes(p2)) == p2

    def test_infinity_serialization(self):
        inf1 = Point.infinity(B1)
        assert g1_to_bytes(inf1)[0] == 0xC0
        assert g1_from_bytes(g1_to_bytes(inf1)).is_infinity()
        inf2 = Point.infinity(B2)
        assert g2_from_bytes(g2_to_bytes(inf2)).is_infinity()

    def test_malformed_rejected(self):
        with pytest.raises(ValueError):
            g1_from_bytes(b"\x00" * 48)  # no compression flag
        with pytest.raises(ValueError):
            g1_from_bytes((P - 1).to_bytes(48, "big"))  # x >= p after masking? flags
        with pytest.raises(ValueError):
            g2_from_bytes(b"\xc0" + b"\x01" * 95)  # dirty infinity


class TestPairing:
    def test_bilinearity(self):
        e = pairing(G1_GEN, G2_GEN)
        assert not e.is_one()
        assert e.pow(R).is_one()
        a, b = 654321, 123456
        assert pairing(G1_GEN * a, G2_GEN * b) == e.pow(a * b)
        assert pairing(G1_GEN * a, G2_GEN) == pairing(G1_GEN, G2_GEN * a)

    def test_inverse_pair_cancels(self):
        assert multi_pairing([(-G1_GEN, G2_GEN), (G1_GEN, G2_GEN)]).is_one()

    def test_infinity_pairs_to_one(self):
        assert pairing(Point.infinity(B1), G2_GEN).is_one()
        assert pairing(G1_GEN, Point.infinity(B2)).is_one()


class TestHashToCurve:
    def test_expand_message_xmd_lengths(self):
        out = expand_message_xmd(b"abc", b"DST", 256)
        assert len(out) == 256
        # deterministic
        assert out == expand_message_xmd(b"abc", b"DST", 256)
        assert out != expand_message_xmd(b"abd", b"DST", 256)

    def test_hash_to_g2_in_subgroup(self):
        for msg in (b"", b"abc", b"\x00" * 32):
            pt = hash_to_g2(msg)
            assert pt.is_on_curve()
            assert g2_subgroup_check(pt)
            assert not pt.is_infinity()

    def test_hash_to_g2_deterministic_and_injective_ish(self):
        assert hash_to_g2(b"m1") == hash_to_g2(b"m1")
        assert hash_to_g2(b"m1") != hash_to_g2(b"m2")


class TestInteropFixture:
    @pytest.mark.skipif(
        not os.path.exists(INTEROP_PUBKEYS_PATH), reason="reference fixture not mounted"
    )
    def test_interop_pubkeys_match_reference_blst_output(self):
        ref = json.load(open(INTEROP_PUBKEYS_PATH))
        mine = ["0x" + pk.hex() for pk in interop_pubkeys(8)]
        assert mine == ref[:8]


class TestSignatures:
    def test_sign_verify(self):
        sk = interop_secret_key(0)
        pk = sk.to_public_key()
        sig = sk.sign(MSG)
        assert verify(pk, MSG, sig)
        assert not verify(pk, b"\x01" * 32, sig)
        assert not verify(interop_secret_key(1).to_public_key(), MSG, sig)

    def test_serialization_roundtrip(self):
        sk = interop_secret_key(2)
        sig = sk.sign(MSG)
        assert Signature.from_bytes(sig.to_bytes()) == sig
        pk = sk.to_public_key()
        assert PublicKey.from_bytes(pk.to_bytes()) == pk
        assert SecretKey.from_bytes(sk.to_bytes()).value == sk.value

    def test_fast_aggregate_verify(self):
        sks = [interop_secret_key(i) for i in range(4)]
        pks = [s.to_public_key() for s in sks]
        agg = aggregate_signatures([s.sign(MSG) for s in sks])
        assert fast_aggregate_verify(pks, MSG, agg)
        assert not fast_aggregate_verify(pks[:3], MSG, agg)
        assert not fast_aggregate_verify([], MSG, agg)

    def test_aggregate_verify_distinct_messages(self):
        sks = [interop_secret_key(i) for i in range(3)]
        pks = [s.to_public_key() for s in sks]
        msgs = [bytes([i]) * 32 for i in range(3)]
        agg = aggregate_signatures([s.sign(m) for s, m in zip(sks, msgs)])
        assert aggregate_verify(pks, msgs, agg)
        assert not aggregate_verify(pks, msgs[::-1], agg)

    def test_batch_verify(self):
        sks = [interop_secret_key(i) for i in range(3)]
        sets = []
        for i, sk in enumerate(sks):
            msg = bytes([i]) * 32
            sets.append((sk.to_public_key(), msg, sk.sign(msg)))
        assert verify_multiple_signatures(sets)
        bad = list(sets)
        bad[1] = (sets[1][0], sets[1][1], sks[2].sign(sets[1][1]))
        assert not verify_multiple_signatures(bad)
        assert not verify_multiple_signatures([])


class TestVerifierBoundary:
    def _sets(self):
        out = []
        for i in range(3):
            sk = interop_secret_key(i)
            msg = bytes([i]) * 32
            out.append(
                SingleSignatureSet(
                    pubkey=sk.to_public_key(),
                    signing_root=msg,
                    signature=sk.sign(msg).to_bytes(),
                )
            )
        return out

    def test_verify_signature_sets(self):
        v = PyBlsVerifier()
        assert v.verify_signature_sets(self._sets())
        assert v.batch_retries == 0

    def test_batch_failure_retries_individually(self):
        v = PyBlsVerifier()
        sets = self._sets()
        sets[1].signature = interop_secret_key(9).sign(sets[1].signing_root).to_bytes()
        assert not v.verify_signature_sets(sets)
        assert v.batch_retries == 1

    def test_aggregated_set(self):
        sks = [interop_secret_key(i) for i in range(4)]
        agg = aggregate_signatures([s.sign(MSG) for s in sks])
        s = AggregatedSignatureSet(
            pubkeys=[s.to_public_key() for s in sks],
            signing_root=MSG,
            signature=agg.to_bytes(),
        )
        v = PyBlsVerifier()
        assert v.verify_signature_sets([s])

    def test_malformed_signature_bytes_rejected_not_raised(self):
        v = PyBlsVerifier()
        sets = self._sets()
        sets[0].signature = b"\x00" * 96
        assert not v.verify_signature_sets(sets)
