"""Overload survival (ISSUE 6, docs/overload.md): QoS priority lanes,
deadline shedding, overflow eviction, backpressure, and the firehose
harness — all against stub verifiers (zero XLA work; the pool's
scheduling layer is the system under test, not the kernel).

Reference behaviors: Lodestar's per-topic gossip job queues (blocks ahead
of attestations, network/processor/gossipQueues) collapsed onto one
lane-ordered JobItemQueue, and BlsMultiThreadWorkerPool's buffering
retuned with admission control (deadline shed / evict-low / high-water
backpressure)."""

import asyncio
import time

import pytest

from lodestar_tpu import tracing
from lodestar_tpu.chain.bls_pool import BlsBatchPool
from lodestar_tpu.chain.validation import (
    GossipAction,
    GossipValidationError,
    _pool_verify,
)
from lodestar_tpu.crypto.bls.verifier import (
    DEFAULT_PRIORITY,
    SignatureSetPriority,
    VerificationDroppedError,
)
from lodestar_tpu.forensics.journal import JOURNAL
from lodestar_tpu.metrics import create_metrics
from lodestar_tpu.network.gossip import GossipRouter, sheddable_topic
from lodestar_tpu.tracing import TRACER
from lodestar_tpu.utils.queue import JobItemQueue, QueueError
from tools.firehose import StubVerifier, percentile, run_firehose

BLOCK = SignatureSetPriority.BLOCK_PROPOSAL
AGG = SignatureSetPriority.AGGREGATE
UNAGG = SignatureSetPriority.UNAGGREGATED
SYNC = SignatureSetPriority.SYNC_COMMITTEE


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_singletons():
    TRACER.disable()
    TRACER.clear()
    JOURNAL.clear()
    yield
    TRACER.disable()
    TRACER.clear()
    JOURNAL.clear()


class RecordingVerifier(StubVerifier):
    """StubVerifier that also records the order sets arrive in dispatches."""

    def __init__(self, **kw):
        kw.setdefault("pack_ms", 0.0)
        kw.setdefault("dispatch_ms", 0.0)
        kw.setdefault("per_set_us", 0.0)
        super().__init__(**kw)
        self.batches = []

    def verify_signature_sets_async(self, sets, deadline=None):
        self.batches.append(list(sets))
        return super().verify_signature_sets_async(sets, deadline)


# -- queue layer -------------------------------------------------------------


class TestQueueLanes:
    def test_drain_order_is_lane_then_fifo(self):
        async def main():
            async def process(x):
                return x

            q = JobItemQueue(process, max_length=100, max_concurrency=0)
            tasks = []
            for item, lane in (
                ("u1", UNAGG), ("s1", SYNC), ("b1", BLOCK),
                ("u2", UNAGG), ("a1", AGG),
            ):
                tasks.append(asyncio.create_task(q.push(item, priority=int(lane))))
            await asyncio.sleep(0)
            batch = q.drain_batch(10)
            assert [item for item, _ in batch] == ["b1", "a1", "u1", "u2", "s1"]
            for item, fut in batch:
                fut.set_result(item)
            await asyncio.gather(*tasks)

        run(main())

    def test_untagged_pushes_keep_single_lane_fifo(self):
        async def main():
            async def process(x):
                return x

            q = JobItemQueue(process, max_length=100, max_concurrency=0)
            tasks = [asyncio.create_task(q.push(i)) for i in range(4)]
            await asyncio.sleep(0)
            batch = q.drain_batch(10)
            assert [item for item, _ in batch] == [0, 1, 2, 3]
            for item, fut in batch:
                fut.set_result(item)
            await asyncio.gather(*tasks)

        run(main())

    def test_evict_low_drops_lowest_lane_first(self):
        async def main():
            async def process(x):
                return x

            q = JobItemQueue(
                process, max_length=3, max_concurrency=0, overflow="evict_low"
            )
            t_sync = asyncio.create_task(q.push("s", priority=int(SYNC)))
            t_un1 = asyncio.create_task(q.push("u1", priority=int(UNAGG)))
            t_un2 = asyncio.create_task(q.push("u2", priority=int(UNAGG)))
            await asyncio.sleep(0)
            # a block push on a full queue evicts the OLDEST job of the
            # LOWEST lane (the sync-committee one), never a peer lane's head
            t_block = asyncio.create_task(q.push("b", priority=int(BLOCK)))
            await asyncio.sleep(0)
            with pytest.raises(QueueError) as ei:
                await t_sync
            assert ei.value.code == "QUEUE_MAX_LENGTH"
            assert len(q) == 3 and q.metrics.dropped_jobs == 1
            batch = q.drain_batch(10)
            assert [item for item, _ in batch] == ["b", "u1", "u2"]
            for item, fut in batch:
                fut.set_result(item)
            await asyncio.gather(t_un1, t_un2, t_block)

        run(main())

    def test_evict_low_rejects_incoming_when_outranked(self):
        async def main():
            async def process(x):
                return x

            q = JobItemQueue(
                process, max_length=2, max_concurrency=0, overflow="evict_low"
            )
            t1 = asyncio.create_task(q.push("b1", priority=int(BLOCK)))
            t2 = asyncio.create_task(q.push("b2", priority=int(BLOCK)))
            await asyncio.sleep(0)
            # everything pending outranks the storm job: the INCOMING pays
            with pytest.raises(QueueError):
                await q.push("u", priority=int(UNAGG))
            assert len(q) == 2
            for item, fut in q.drain_batch(10):
                fut.set_result(item)
            await asyncio.gather(t1, t2)

        run(main())

    def test_eviction_loops_past_done_futures(self):
        """Satellite regression: the pre-round-10 LIFO overflow popped ONE
        entry and stopped even when that future was already done (cancelled
        pusher) — leaving the queue over max_length while counting a drop
        that freed nothing.  The loop must reap done entries (no drop
        counted) until a LIVE job is actually evicted."""

        async def main():
            async def process(x):
                return x

            q = JobItemQueue(
                process, max_length=3, max_concurrency=0, overflow="evict_oldest"
            )
            tasks = [
                asyncio.create_task(q.push(i, priority=int(UNAGG)))
                for i in range(3)
            ]
            await asyncio.sleep(0)
            # cancel the two oldest pushers: their futures are done but the
            # entries still occupy queue slots
            tasks[0].cancel()
            tasks[1].cancel()
            await asyncio.sleep(0)
            assert len(q) == 3  # stale entries still counted
            t_new = asyncio.create_task(q.push(99, priority=int(UNAGG)))
            await asyncio.sleep(0)
            # the done entry is reaped to make room — NOT counted as a
            # drop (nobody was waiting on it), and the queue never sits
            # over max_length
            assert len(q) <= q.max_length
            assert q.metrics.dropped_jobs == 0
            with pytest.raises(asyncio.CancelledError):
                await tasks[0]
            # the LIVE job was not sacrificed while dead weight remained
            batch = q.drain_batch(10)
            assert [item for item, _ in batch] == [2, 99]
            for item, fut in batch:
                fut.set_result(item)
            await asyncio.gather(tasks[2], t_new)
            # a queue holding ONLY live jobs at capacity does evict one
            t_a = asyncio.create_task(q.push("a", priority=int(UNAGG)))
            t_b = asyncio.create_task(q.push("b", priority=int(UNAGG)))
            t_c = asyncio.create_task(q.push("c", priority=int(UNAGG)))
            await asyncio.sleep(0)
            t_d = asyncio.create_task(q.push("d", priority=int(UNAGG)))
            await asyncio.sleep(0)
            with pytest.raises(QueueError):
                await t_a  # oldest live job paid
            assert q.metrics.dropped_jobs == 1 and len(q) == 3
            for item, fut in q.drain_batch(10):
                fut.set_result(item)
            await asyncio.gather(t_b, t_c, t_d)

        run(main())

    def test_evict_low_reaps_dead_entries_in_outranking_lanes(self):
        """A queue full of cancelled-pusher corpses in HIGHER lanes must
        not reject a live lower-lane push: dead-entry reaping happens
        before the lane-rank rule (reaping frees a slot without dropping
        anyone, whatever lane the corpse sat in)."""

        async def main():
            async def process(x):
                return x

            q = JobItemQueue(
                process, max_length=2, max_concurrency=0,
                overflow="evict_low", size_fn=len,
            )
            t1 = asyncio.create_task(q.push([1], priority=int(BLOCK)))
            t2 = asyncio.create_task(q.push([2], priority=int(BLOCK)))
            await asyncio.sleep(0)
            t1.cancel()
            t2.cancel()
            await asyncio.sleep(0)
            assert len(q) == 2 and q.pending_size == 2  # corpses counted
            t3 = asyncio.create_task(q.push([3], priority=int(SYNC)))
            await asyncio.sleep(0)
            assert q.metrics.dropped_jobs == 0  # reaped, nothing dropped
            # one corpse reaped (enough for room); the other drops out at
            # drain time
            assert q.pending_size == 2
            batch = q.drain_batch(10)
            assert [item for item, _ in batch] == [[3]]
            assert q.pending_size == 0
            for item, fut in batch:
                fut.set_result(item)
            await t3

        run(main())

    def test_evict_low_sweeps_buried_corpses_before_refusing(self):
        """Refusal path: everything pending outranks the incoming job,
        but some of it is corpses buried BEHIND a live head — the sweep
        must reap one instead of dropping the live incoming job."""

        async def main():
            async def process(x):
                return x

            q = JobItemQueue(
                process, max_length=3, max_concurrency=0, overflow="evict_low"
            )
            t_live = asyncio.create_task(q.push("b-live", priority=int(BLOCK)))
            t_c1 = asyncio.create_task(q.push("b-dead1", priority=int(BLOCK)))
            t_c2 = asyncio.create_task(q.push("b-dead2", priority=int(BLOCK)))
            await asyncio.sleep(0)
            t_c1.cancel()
            t_c2.cancel()
            await asyncio.sleep(0)
            # lane-0 head is live, corpses sit behind it; an incoming
            # lane-3 job is outranked by every entry — yet must get in
            t_sync = asyncio.create_task(q.push("s", priority=int(SYNC)))
            await asyncio.sleep(0)
            assert q.metrics.dropped_jobs == 0
            batch = q.drain_batch(10)
            assert [item for item, _ in batch] == ["b-live", "s"]
            for item, fut in batch:
                fut.set_result(item)
            await asyncio.gather(t_live, t_sync)

        run(main())

    def test_pending_size_tracks_push_drain_evict_abort(self):
        """Satellite regression: pending_size is the O(1) running sum of
        size_fn over pending jobs — correct through every mutation path."""

        async def main():
            async def process(x):
                return x

            q = JobItemQueue(
                process, max_length=3, max_concurrency=0,
                overflow="evict_oldest", size_fn=len,
            )
            t1 = asyncio.create_task(q.push([1, 2, 3]))
            t2 = asyncio.create_task(q.push([4]))
            t3 = asyncio.create_task(q.push([5, 6]))
            await asyncio.sleep(0)
            assert q.pending_size == 6
            # overflow evicts the oldest ([1,2,3]): -3
            t4 = asyncio.create_task(q.push([7, 8]))
            await asyncio.sleep(0)
            assert q.pending_size == 5
            with pytest.raises(QueueError):
                await t1
            batch = q.drain_batch(1)  # drains [4]
            assert q.pending_size == 4
            for item, fut in batch:
                fut.set_result(True)
            q.abort()
            assert q.pending_size == 0
            await t2
            for t in (t3, t4):
                with pytest.raises(QueueError):
                    await t

        run(main())

    def test_drain_batch_max_size_keeps_batches_dispatch_sized(self):
        async def main():
            async def process(x):
                return x

            q = JobItemQueue(process, max_length=100, max_concurrency=0, size_fn=len)
            tasks = [
                asyncio.create_task(q.push([i] * 3)) for i in range(4)
            ]
            await asyncio.sleep(0)
            batch = q.drain_batch(10, max_size=6)
            assert len(batch) == 2  # 3 + 3 sets; a third job would cross 6
            oversized = q.drain_batch(10, max_size=1)
            assert len(oversized) == 1  # always takes at least one job
            for item, fut in batch + oversized + q.drain_batch(10):
                fut.set_result(item)
            await asyncio.gather(*tasks)

        run(main())


# -- pool layer --------------------------------------------------------------


class TestPoolLanes:
    def test_block_lane_dispatches_ahead_of_storm_backlog(self):
        """A block proposal pushed AFTER a storm of unaggregated jobs still
        rides the first merged batch: the queue hands lanes back in
        priority order at drain time."""

        async def main():
            v = RecordingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.02, flush_threshold=10_000)
            jobs = [
                asyncio.create_task(
                    pool.verify_signature_sets([("unagg", i)], priority=UNAGG)
                )
                for i in range(50)
            ]
            jobs.append(
                asyncio.create_task(
                    pool.verify_signature_sets([("block", 0)], priority=BLOCK)
                )
            )
            results = await asyncio.gather(*jobs)
            assert results == [True] * 51
            assert v.batches[0][0] == ("block", 0)
            pool.close()

        run(main())

    def test_deadline_shed_resolves_typed_error_not_false(self):
        async def main():
            v = RecordingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.01)
            live = asyncio.create_task(
                pool.verify_signature_sets([("live", 0)], priority=UNAGG)
            )
            expired = asyncio.create_task(
                pool.verify_signature_sets(
                    [("stale", 0), ("stale", 1)],
                    priority=SYNC,
                    deadline=time.monotonic() - 0.001,
                )
            )
            assert await live is True
            with pytest.raises(VerificationDroppedError) as ei:
                await expired
            assert ei.value.reason == "deadline"
            assert ei.value.lane == SYNC
            # the shed job never reached the verifier; the drop is
            # accounted in sets under (reason, lane)
            assert all(("stale", 0) not in b for b in v.batches)
            assert pool.dropped_sets == {("deadline", "sync_committee"): 2}
            pool.close()

        run(main())

    def test_deadline_shed_emits_span_and_journal(self):
        async def main():
            tracing.enable(1024)
            JOURNAL.enabled = True
            v = RecordingVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.01)
            with pytest.raises(VerificationDroppedError):
                await pool.verify_signature_sets(
                    [("stale", 0)], priority=UNAGG,
                    deadline=time.monotonic() - 0.001,
                )
            shed = [s for s in TRACER.spans() if s.name == "bls.shed"]
            assert len(shed) == 1
            assert shed[0].args["reason"] == "deadline"
            assert shed[0].args["lane"] == "unaggregated"
            assert any(e["kind"] == "pool.shed" for e in JOURNAL.events())
            pool.close()

        run(main())

    def test_overflow_eviction_maps_to_dropped_error(self):
        """Queue overflow under evict_low surfaces to BOTH victims as
        VerificationDroppedError("overflow") — the evicted pending job and
        an outranked incoming job — never QueueError or False."""

        async def main():
            v = RecordingVerifier()
            pool = BlsBatchPool(
                v, max_buffer_wait=5.0, flush_threshold=10_000, max_queue_length=2
            )
            t_sync = asyncio.create_task(
                pool.verify_signature_sets([("sync", 0)], priority=SYNC)
            )
            t_un = asyncio.create_task(
                pool.verify_signature_sets([("unagg", 0)], priority=UNAGG)
            )
            await asyncio.sleep(0.01)
            # block evicts the pending sync job (lowest lane first)
            t_block = asyncio.create_task(
                pool.verify_signature_sets([("block", 0)], priority=BLOCK)
            )
            with pytest.raises(VerificationDroppedError) as ei:
                await t_sync
            assert ei.value.reason == "overflow" and ei.value.lane == SYNC
            # an incoming sync job outranked by everything pending pays
            with pytest.raises(VerificationDroppedError) as ei2:
                await pool.verify_signature_sets([("sync", 1)], priority=SYNC)
            assert ei2.value.reason == "overflow" and ei2.value.lane == SYNC
            assert pool.dropped_sets == {("overflow", "sync_committee"): 2}
            # every push-time drop leaves journal evidence too
            drops = [e for e in JOURNAL.events() if e["kind"] == "pool.drop"]
            assert len(drops) == 2
            assert all(e["reason"] == "overflow" for e in drops)
            pool._schedule_flush(0.0)
            assert await asyncio.gather(t_un, t_block) == [True, True]
            pool.close()

        run(main())

    def test_backpressure_high_water_toggles_with_hysteresis(self):
        async def main():
            v = RecordingVerifier()
            pool = BlsBatchPool(
                v, max_buffer_wait=5.0, flush_threshold=10_000,
                max_queue_length=100, high_water=10,
            )
            assert pool.low_water == 5
            jobs = [
                asyncio.create_task(
                    pool.verify_signature_sets([("u", i)], priority=UNAGG)
                )
                for i in range(9)
            ]
            await asyncio.sleep(0.01)
            assert not pool.overloaded  # 9 < high water
            jobs.append(
                asyncio.create_task(
                    pool.verify_signature_sets([("u", 9)], priority=UNAGG)
                )
            )
            await asyncio.sleep(0.01)
            assert pool.overloaded  # 10 >= high water
            pool._schedule_flush(0.0)
            assert await asyncio.gather(*jobs) == [True] * 10
            assert not pool.overloaded  # drained below low water
            pool.close()

        run(main())

    def test_close_during_flush_strands_nothing(self):
        """Satellite regression: close() while a flush has batches in
        flight — every already-drained job future still resolves, and the
        per-job retry loop respects _closed (typed shutdown drop, no
        stranded awaits, no further verifier calls)."""

        async def main():
            release = __import__("threading").Event()

            class BlockingFalseVerifier(RecordingVerifier):
                """First merged verdict blocks until released, then returns
                False so the pool enters the per-job retry loop."""

                def verify_signature_sets_async(self, sets, deadline=None):
                    self.batches.append(list(sets))
                    self.dispatches += 1

                    class _Pending:
                        device = "stub:0"

                        def result(_self):
                            release.wait(5.0)
                            return False

                    return _Pending()

            v = BlockingFalseVerifier()
            retried = []
            real_single = v.verify_signature_sets
            v.verify_signature_sets = lambda sets: retried.append(sets) or True
            pool = BlsBatchPool(v, max_buffer_wait=0.005, pipeline_depth=1)
            jobs = [
                asyncio.create_task(
                    pool.verify_signature_sets([("j", i)], priority=UNAGG)
                )
                for i in range(3)
            ]
            await asyncio.sleep(0.05)  # batch drained + in flight
            assert v.dispatches == 1
            pool.close()
            release.set()
            results = await asyncio.wait_for(
                asyncio.gather(*jobs, return_exceptions=True), timeout=5.0
            )
            # nothing stranded: every future resolved, each with the typed
            # shutdown drop (the batch failed and retry found the pool closed)
            assert len(results) == 3
            for r in results:
                assert isinstance(r, VerificationDroppedError)
                assert r.reason == "shutdown"
            assert retried == []  # _closed checked before any retry dispatch
            assert pool.dropped_sets == {("shutdown", "unaggregated"): 3}
            del real_single

        run(main())

    def test_close_with_buffered_jobs_raises_typed_shutdown(self):
        """close() while jobs are still BUFFERED (never drained): the
        queue abort must surface as VerificationDroppedError('shutdown'),
        not a raw QueueError — block import and backfill are written
        around the typed contract."""

        async def main():
            pool = BlsBatchPool(
                RecordingVerifier(), max_buffer_wait=30.0,
                flush_threshold=10_000,
            )
            jobs = [
                asyncio.create_task(
                    pool.verify_signature_sets([("j", i)], priority=UNAGG)
                )
                for i in range(3)
            ]
            await asyncio.sleep(0.01)
            pool.close()
            results = await asyncio.gather(*jobs, return_exceptions=True)
            for r in results:
                assert isinstance(r, VerificationDroppedError)
                assert r.reason == "shutdown"
            assert pool.dropped_sets == {("shutdown", "unaggregated"): 3}

        run(main())

    def test_pusher_cancelled_mid_retry_does_not_kill_flusher(self):
        """A caller cancelled while its job is being retried individually
        cancels the job future; the retry loop must not set_result on it
        (InvalidStateError would kill the flusher and strand every other
        in-flight job)."""

        async def main():
            import threading

            release = threading.Event()

            class SlowRetryVerifier(RecordingVerifier):
                def verify_signature_sets_async(self, sets, deadline=None):
                    self.batches.append(list(sets))

                    class _Pending:
                        device = "stub:0"

                        def result(_self):
                            return False  # force retry-individually

                    return _Pending()

                def verify_signature_sets(self, sets):
                    release.wait(5.0)  # per-job retry blocks until released
                    return True

            v = SlowRetryVerifier()
            pool = BlsBatchPool(v, max_buffer_wait=0.005, pipeline_depth=1)
            t_a = asyncio.create_task(
                pool.verify_signature_sets([("a", 0)], priority=UNAGG)
            )
            t_b = asyncio.create_task(
                pool.verify_signature_sets([("b", 0)], priority=UNAGG)
            )
            await asyncio.sleep(0.05)  # merged batch failed; retry of A blocked
            t_a.cancel()  # cancels A's job future mid-retry-await
            await asyncio.sleep(0.01)
            release.set()
            with pytest.raises(asyncio.CancelledError):
                await t_a
            # the flusher survived and resolved B
            assert await asyncio.wait_for(t_b, timeout=5.0) is True
            pool.close()

        run(main())

    def test_drop_metrics_labelled_by_reason_and_lane(self):
        async def main():
            m = create_metrics()
            pool = BlsBatchPool(RecordingVerifier(), max_buffer_wait=0.01, metrics=m)
            with pytest.raises(VerificationDroppedError):
                await pool.verify_signature_sets(
                    [("s", 0)], priority=SYNC, deadline=time.monotonic() - 1
                )
            text = m.reg.expose().decode()
            assert (
                'lodestar_bls_pool_dropped_total{lane="sync_committee",'
                'reason="deadline"} 1.0' in text
                or 'lodestar_bls_pool_dropped_total{reason="deadline",'
                'lane="sync_committee"} 1.0' in text
            )
            assert "lodestar_bls_pool_lane_pending" in text
            assert "lodestar_bls_pool_backpressure" in text
            pool.close()

        run(main())


# -- overload bundle ---------------------------------------------------------


class TestOverloadBundle:
    def test_shed_rate_spike_writes_one_triageable_bundle(self, tmp_path):
        from lodestar_tpu.forensics.bundle import latest_bundle
        from lodestar_tpu.forensics.recorder import RECORDER
        from tools.inspect_bundle import summarize, validate

        saved = (RECORDER._dir, RECORDER.metrics, RECORDER.pool, RECORDER.verifier)
        try:
            async def main():
                v = RecordingVerifier()
                pool = BlsBatchPool(
                    v, max_buffer_wait=0.01,
                    overload_shed_threshold=4, overload_cooldown_s=60.0,
                )
                RECORDER.configure(forensics_dir=str(tmp_path), pool=pool)
                stale = time.monotonic() - 0.001
                for i in range(6):
                    with pytest.raises(VerificationDroppedError):
                        await pool.verify_signature_sets(
                            [("s", i)], priority=UNAGG, deadline=stale
                        )
                assert pool._overload_task is not None
                await pool._overload_task  # the to_thread dump
                pool.close()

            run(main())
            bundle = latest_bundle(str(tmp_path))
            assert bundle and "overload" in bundle
            assert validate(bundle) == []
            ov = summarize(bundle)["overload"]
            # the dump fires the moment the threshold is crossed (drop 4);
            # later drops land after the snapshot
            assert ov["shed_window_sets"] >= 4
            assert ov["dropped_by_lane"]["unaggregated"] >= 4
            assert ov["dropped_by_reason"]["deadline"] >= 4
            assert "queue_depth_jobs" in ov and "pending_sets" in ov
        finally:
            RECORDER._dir, RECORDER.metrics, RECORDER.pool, RECORDER.verifier = saved

    def test_disabled_threshold_keeps_shed_window_empty(self):
        """--bls-overload-bundle-threshold 0 disables bundles — the
        rate window must not keep accumulating drop tuples forever on a
        node that sheds for the life of the process."""

        async def main():
            pool = BlsBatchPool(
                RecordingVerifier(), max_buffer_wait=0.01,
                overload_shed_threshold=0,
            )
            stale = time.monotonic() - 0.001
            for i in range(50):
                with pytest.raises(VerificationDroppedError):
                    await pool.verify_signature_sets(
                        [("s", i)], priority=UNAGG, deadline=stale
                    )
            assert len(pool._shed_window) == 0
            assert pool._overload_task is None
            assert pool.dropped_sets == {("deadline", "unaggregated"): 50}
            pool.close()

        run(main())

    def test_cooldown_rate_limits_bundles(self, tmp_path):
        import os

        from lodestar_tpu.forensics.recorder import RECORDER

        saved = (RECORDER._dir, RECORDER.metrics, RECORDER.pool, RECORDER.verifier)
        try:
            async def main():
                pool = BlsBatchPool(
                    RecordingVerifier(), max_buffer_wait=0.01,
                    overload_shed_threshold=2, overload_cooldown_s=3600.0,
                )
                RECORDER.configure(forensics_dir=str(tmp_path), pool=pool)
                stale = time.monotonic() - 0.001
                for i in range(20):
                    with pytest.raises(VerificationDroppedError):
                        await pool.verify_signature_sets(
                            [("s", i)], priority=UNAGG, deadline=stale
                        )
                if pool._overload_task is not None:
                    await pool._overload_task
                pool.close()

            run(main())
            bundles = [d for d in os.listdir(tmp_path) if "overload" in d]
            assert len(bundles) == 1  # cooldown held: one dump for 20 drops
        finally:
            RECORDER._dir, RECORDER.metrics, RECORDER.pool, RECORDER.verifier = saved


# -- upstream contract -------------------------------------------------------


class TestUpstreamContract:
    def test_dropped_job_maps_to_ignore_not_reject(self):
        class ShedPool:
            async def verify_signature_sets(self, sets, batchable=True, priority=None):
                raise VerificationDroppedError("deadline", DEFAULT_PRIORITY)

        async def main():
            with pytest.raises(GossipValidationError) as ei:
                await _pool_verify(ShedPool(), [object()], priority=UNAGG)
            assert ei.value.action == GossipAction.IGNORE

        run(main())

    def test_legacy_pool_without_priority_kwarg_still_works(self):
        class LegacyPool:
            def __init__(self):
                self.calls = []

            async def verify_signature_sets(self, sets, batchable=True):
                self.calls.append((len(sets), batchable))
                return True

        async def main():
            pool = LegacyPool()
            assert await _pool_verify(pool, [object()], priority=BLOCK) is True
            assert pool.calls == [(1, True)]

        run(main())

    def test_backfill_shed_batch_does_not_penalize_peer(self):
        """A pool-shed backfill batch (overload admission) must retry
        without scoring the serving peer; a real failure still penalizes."""
        from lodestar_tpu.config.chain_config import ChainConfig
        from lodestar_tpu.params.presets import MINIMAL
        from lodestar_tpu.sync.backfill import BackfillSync

        class FakeDb:
            def get_archived_block_by_root(self, root):
                return None

            class block:  # noqa: N801 - attribute shim
                @staticmethod
                def get(root):
                    return None

        class FakePeer:
            def __init__(self):
                self.penalties = []
                self.score = 0
                self.status = type("S", (), {"head_slot": 100})()

                class RR:
                    async def blocks_by_range(_self, start, count):
                        return [object()]

                self.reqresp = RR()

            def penalize(self, n):
                self.penalties.append(n)

        class FakePeers:
            def __init__(self, peer):
                self._peer = peer

            def connected(self):
                return [self._peer]

        async def main():
            peer = FakePeer()
            bf = BackfillSync(
                MINIMAL, ChainConfig(PRESET_BASE="minimal"), FakeDb(), None,
                None, b"\x00" * 32, FakePeers(peer),
            )
            bf.oldest_slot = 80  # pretend the anchor resolved
            bf.shed_backoff_s = 0.0
            bf._links = lambda blocks: True

            async def shed(blocks):
                raise VerificationDroppedError("overflow", UNAGG)

            bf._verify_and_store = shed
            await bf.run(max_batches=2)
            assert peer.penalties == []  # admission decision, peer innocent

            async def broken(blocks):
                raise ValueError("bad history")

            bf._verify_and_store = broken
            await bf.run(max_batches=1)
            assert peer.penalties == [10]  # real failures still score

        run(main())

    def test_block_import_maps_drop_to_block_error(self):
        """_verify_block_sets: a pool that sheds the job (shutdown
        mid-retry) must surface BlockError to the import stack, never the
        pool's typed error (REST publish / unknown-block sync are written
        around the BlockError contract)."""
        from lodestar_tpu.chain.beacon_chain import BeaconChain, BlockError

        class ShedBls:
            async def verify_signature_sets(self, sets, priority=None):
                raise VerificationDroppedError("shutdown", priority)

        class FakeChain:
            bls = ShedBls()

        async def main():
            with pytest.raises(BlockError) as ei:
                await BeaconChain._verify_block_sets(FakeChain(), [object()])
            assert "dropped" in str(ei.value) and "shutdown" in str(ei.value)

        run(main())

    def test_gossip_intake_sheds_storm_topics_under_backpressure(self):
        assert sheddable_topic("beacon_attestation_7")
        assert sheddable_topic("sync_committee_3")
        assert not sheddable_topic("beacon_block")
        assert not sheddable_topic("beacon_aggregate_and_proof")
        assert not sheddable_topic("sync_committee_contribution_and_proof")

        async def main():
            overloaded = {"on": True}
            router = GossipRouter(backpressure=lambda: overloaded["on"])
            seen = []

            async def handler(data):
                seen.append(data)

            router.subscribe("beacon_attestation_1", handler)
            router.subscribe("beacon_block", handler)
            await router.on_message("beacon_attestation_1", b"a1")
            await router.on_message("beacon_block", b"b1")
            assert seen == [b"b1"]  # storm topic shed, block flowed
            assert router.backpressure_dropped == 1
            overloaded["on"] = False
            await router.on_message("beacon_attestation_1", b"a2")
            assert seen == [b"b1", b"a2"]

        run(main())


# -- firehose ---------------------------------------------------------------


class TestFirehose:
    def test_percentile_nearest_rank(self):
        assert percentile([], 99) is None
        assert percentile([5.0], 50) == 5.0
        vals = list(range(1, 101))
        assert percentile(vals, 50) == 50
        assert percentile(vals, 99) == 99
        assert percentile(vals, 100) == 100
        assert percentile([1, 100], 50) == 1  # nearest rank, not round-half-even

    def test_smoke_sustained_run_accounts_for_everything(self):
        """Seconds-scale stub firehose: modest offered load, zero drops,
        every offered set accounted, queue-wait spans captured."""

        async def main():
            tracing.enable(65536)
            pool = BlsBatchPool(
                StubVerifier(), max_buffer_wait=0.01, flush_threshold=128
            )
            try:
                return await run_firehose(
                    pool, rate=800.0, duration_s=1.0, deadline_ms=2000.0
                )
            finally:
                pool.close()

        report = run(main())
        assert report["stranded_futures"] == 0
        assert report["unaccounted_sets"] == 0
        assert report["dropped_sets_total"] == 0
        assert report["verified_sets"] > 0
        assert report["queue_wait"]["n"] > 0
        assert report["queue_wait"]["p99_ms"] is not None
        assert report["e2e"]["p99_ms"] is not None
        assert set(report["outcomes"]) == {"verified_ok"}

    def test_errored_jobs_stay_accounted(self):
        """A verifier that raises must not break the accounting identity:
        errored sets are their own accounted category, not 'unaccounted'."""

        class RaisingVerifier(StubVerifier):
            def verify_signature_sets_async(self, sets, deadline=None):
                raise RuntimeError("boom")

            def verify_signature_sets(self, sets):
                raise RuntimeError("boom")

        async def main():
            tracing.enable(4096)
            pool = BlsBatchPool(
                RaisingVerifier(), max_buffer_wait=0.01, flush_threshold=16
            )
            try:
                return await run_firehose(pool, rate=300.0, duration_s=0.5)
            finally:
                pool.close()

        report = run(main())
        assert report["errored_sets"] > 0
        assert report["unaccounted_sets"] == 0
        assert report["stranded_futures"] == 0
        assert all(o.startswith("error_") for o in report["outcomes"])

    def test_smoke_overload_run_bounded_and_accounted(self):
        """Offered load far beyond the stub's capacity: the run completes
        with bounded queue memory, zero stranded futures, every drop
        typed and accounted, and backpressure engaged at some point
        (intake shed > 0)."""

        async def main():
            tracing.enable(65536)
            pool = BlsBatchPool(
                StubVerifier(per_set_us=500.0),  # ~2k sets/s ceiling
                max_buffer_wait=0.01, flush_threshold=128,
                max_queue_length=512, overload_shed_threshold=0,
            )
            try:
                report = await run_firehose(
                    pool, rate=8000.0, duration_s=1.5, deadline_ms=300.0
                )
                report["max_pending"] = pool.pending_sets()
                return report
            finally:
                pool.close()

        report = run(main())
        assert report["stranded_futures"] == 0
        assert report["unaccounted_sets"] == 0
        assert report["intake_shed_total"] > 0  # backpressure engaged
        assert report["pending_sets_after"] <= 512  # bounded queue
        # drops (if any) are all typed reason/lane keys
        for key in report["dropped_sets"]:
            reason, lane = key.split("/")
            assert reason in ("deadline", "overflow", "shutdown")
            assert lane in (
                "block_proposal", "aggregate", "unaggregated", "sync_committee"
            )


# -- tooling ----------------------------------------------------------------


class TestTooling:
    def test_check_trace_accepts_shed_span(self):
        from tools.check_trace import validate, validate_pipeline

        shed_ev = {
            "name": "bls.shed", "ph": "X", "pid": 1, "tid": 1,
            "ts": 10.0, "dur": 5.0, "cat": "pool",
            "args": {"cid": 7, "lane": "unaggregated", "reason": "deadline"},
        }
        assert validate([shed_ev]) == []
        # a fully-shed cid is excluded from the broken-pipeline report
        errs = validate_pipeline([shed_ev], min_batches=1)
        assert len(errs) == 1 and "1 shed batches excluded" in errs[0]

    def test_inspect_bundle_summary_includes_overload(self, tmp_path):
        import json

        from tools.inspect_bundle import summarize

        bundle = tmp_path / "b"
        bundle.mkdir()
        (bundle / "manifest.json").write_text(json.dumps({
            "reason": "overload",
            "overload": {
                "shed_window_sets": 300, "window_s": 10.0,
                "dropped_by_lane": {"unaggregated": 250, "sync_committee": 50},
                "dropped_by_reason": {"deadline": 300},
                "queue_depth_jobs": 412, "pending_sets": 1800,
                "backpressure": True,
            },
        }))
        s = summarize(str(bundle))
        assert s["overload"]["shed_window_sets"] == 300
        assert s["overload"]["dropped_by_lane"]["unaggregated"] == 250
