"""Spec-vector breadth: altair/bellatrix categories, phase0 operation
coverage with invalid cases, ssz_static depth + corrupt-encoding vectors,
and a mainnet-preset tree.

Extends tools/gen_spec_vectors.py (which owns the minimal phase0 core and
calls into this module from its main).  Same contract: official
ethereum/consensus-spec-tests directory format, self-generated (zero
egress — see gen_spec_vectors.py header for what that does and does not
evidence), byte-compatible with the official tree.

Reference for the category set: the reference consumes 12 runners x 3
forks x 2 presets (packages/beacon-node/test/spec/presets/*.ts,
checkCoverage.ts); invalid operation vectors carry no post file and the
runner must observe a failure.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lodestar_tpu.chain.bls_pool import BlsBatchPool  # noqa: E402
from lodestar_tpu.config.chain_config import ChainConfig  # noqa: E402
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier  # noqa: E402
from lodestar_tpu.node.dev_chain import DevChain, clone_state  # noqa: E402
from lodestar_tpu.params import (  # noqa: E402
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_VOLUNTARY_EXIT,
    MAINNET,
    MINIMAL,
)
from lodestar_tpu.ssz import Fields  # noqa: E402
from lodestar_tpu.state_transition import (  # noqa: E402
    EpochContext,
    compute_epoch_at_slot,
    compute_signing_root,
    get_domain,
    process_slots,
    state_transition,
)
from lodestar_tpu.crypto.bls.api import interop_secret_key  # noqa: E402
from lodestar_tpu.types import get_types  # noqa: E402

# shared low-level writers from the core generator.  When the core
# generator runs as a script it lives in sys.modules as "__main__"; alias
# it so the from-import below reuses that module instead of executing
# tools/gen_spec_vectors.py a second time under its own name (two CFG/ROOT
# instances otherwise).
_main = sys.modules.get("__main__")
if (
    "gen_spec_vectors" not in sys.modules
    and _main is not None
    and getattr(_main, "__file__", "").endswith("gen_spec_vectors.py")
):
    sys.modules["gen_spec_vectors"] = _main
from gen_spec_vectors import (  # noqa: E402
    CFG,
    CFG_ALTAIR,
    case_dir,
    canonical_blocks,
    write_ssz,
    write_yaml,
)

T = get_types(MINIMAL)
TM = get_types(MAINNET)

CFG_BELLA = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=1, BELLATRIX_FORK_EPOCH=2,
)
CFG_MAINNET = ChainConfig(
    PRESET_BASE="mainnet", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)


def _types(preset):
    return T if preset is MINIMAL else TM


def state_bytes_p(preset, fork: str, state) -> bytes:
    return getattr(_types(preset), fork).BeaconState.serialize(state)


def block_bytes_p(preset, fork: str, signed) -> bytes:
    return getattr(_types(preset), fork).SignedBeaconBlock.serialize(signed)


async def build_chain_p(preset, cfg, slots: int, n_validators: int = 16) -> DevChain:
    pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.001)
    dev = DevChain(preset, cfg, n_validators, pool)
    await dev.run(slots)
    return dev


def _state_at(dev: DevChain, preset, cfg, slot: int):
    """Canonical post-state advanced to exactly `slot` — from the hot state
    cache when available, else replayed from genesis (early states get
    archived once finality passes them)."""
    root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, slot)
    hot = dev.chain.get_state_by_block_root(root) if root else None
    if hot is not None:
        st = clone_state(preset, hot)
    else:
        st = clone_state(preset, dev.chain.genesis_state)
        for b in canonical_blocks(dev, 1, slot):
            st, _ = state_transition(
                preset, cfg, st, b, verify_proposer_signature=False,
                verify_signatures=False, verify_state_root=True,
            )
    if st.slot < slot:
        process_slots(preset, cfg, st, slot)
    return st


# =============================== altair =====================================


def gen_altair_sanity_finality(dev_a: DevChain) -> None:
    """altair sanity/blocks, sanity/slots, finality/finality from the
    post-fork segment of the altair chain (fork at epoch 1)."""
    spe = MINIMAL.SLOTS_PER_EPOCH
    # sanity/blocks: two post-fork blocks
    pre = _state_at(dev_a, MINIMAL, CFG_ALTAIR, spe + 2)
    blocks = canonical_blocks(dev_a, spe + 3, spe + 4)
    post = clone_state(MINIMAL, pre)
    for b in blocks:
        post, _ = state_transition(
            MINIMAL, CFG_ALTAIR, post, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    d = case_dir("altair", "sanity", "blocks", "pyspec_tests", "two_blocks")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre))
    for i, b in enumerate(blocks):
        write_ssz(d, f"blocks_{i}", block_bytes_p(MINIMAL, "altair", b))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "altair", post))
    write_yaml(d, "meta", {"blocks_count": len(blocks)})

    # sanity/slots: altair state across an epoch boundary (epoch pipeline
    # incl. participation rotation + inactivity updates)
    pre2 = _state_at(dev_a, MINIMAL, CFG_ALTAIR, 2 * spe - 2)
    post2 = clone_state(MINIMAL, pre2)
    process_slots(MINIMAL, CFG_ALTAIR, post2, post2.slot + spe)
    d = case_dir("altair", "sanity", "slots", "pyspec_tests", "over_epoch_boundary")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre2))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "altair", post2))
    write_yaml(d, "slots", spe)

    # finality/finality: two full post-fork epochs advance finalization
    pre3 = _state_at(dev_a, MINIMAL, CFG_ALTAIR, 2 * spe)
    blocks3 = canonical_blocks(dev_a, 2 * spe + 1, 4 * spe)
    post3 = clone_state(MINIMAL, pre3)
    for b in blocks3:
        post3, _ = state_transition(
            MINIMAL, CFG_ALTAIR, post3, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    assert post3.finalized_checkpoint.epoch > pre3.finalized_checkpoint.epoch
    d = case_dir("altair", "finality", "finality", "pyspec_tests", "two_epochs_finalize")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre3))
    for i, b in enumerate(blocks3):
        write_ssz(d, f"blocks_{i}", block_bytes_p(MINIMAL, "altair", b))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "altair", post3))
    write_yaml(d, "meta", {"blocks_count": len(blocks3)})


def gen_altair_rewards(dev_a: DevChain) -> None:
    """altair rewards/basic + rewards/leak: per-flag deltas in the official
    altair file set (source/target/head/inactivity — no inclusion_delay
    post-altair)."""
    from lodestar_tpu.state_transition.altair import (
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_SOURCE_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
        get_flag_index_deltas,
        get_inactivity_penalty_deltas,
    )
    from gen_spec_vectors import _deltas_type

    dt = _deltas_type()
    spe = MINIMAL.SLOTS_PER_EPOCH
    flag_stems = {
        TIMELY_SOURCE_FLAG_INDEX: "source_deltas",
        TIMELY_TARGET_FLAG_INDEX: "target_deltas",
        TIMELY_HEAD_FLAG_INDEX: "head_deltas",
    }

    def emit(handler: str, name: str, state) -> None:
        d = case_dir("altair", "rewards", handler, "pyspec_tests", name)
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", state))
        for flag, stem in flag_stems.items():
            rewards, penalties = get_flag_index_deltas(MINIMAL, state, flag)
            write_ssz(d, stem, dt.serialize(Fields(
                rewards=[int(x) for x in rewards],
                penalties=[int(x) for x in penalties],
            )))
        inactivity = get_inactivity_penalty_deltas(MINIMAL, CFG_ALTAIR, state)
        write_ssz(d, "inactivity_penalty_deltas", dt.serialize(Fields(
            rewards=[0] * len(inactivity), penalties=[int(x) for x in inactivity],
        )))

    emit("basic", "mid_chain", _state_at(dev_a, MINIMAL, CFG_ALTAIR, 3 * spe - 1))

    # leak: a post-fork state advanced blocklessly past the inactivity
    # threshold (finality stalls, scores accumulate via process_slots)
    leak = _state_at(dev_a, MINIMAL, CFG_ALTAIR, 2 * spe)
    process_slots(
        MINIMAL, CFG_ALTAIR, leak,
        leak.slot + (MINIMAL.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 3) * spe,
    )
    assert get_inactivity_penalty_deltas(MINIMAL, CFG_ALTAIR, leak).any(), (
        "altair leak vector must hit the leak branch"
    )
    emit("leak", "stalled_finality", leak)


def gen_altair_operations(dev_a: DevChain) -> None:
    """altair operations/attestation + operations/sync_aggregate (valid and
    invalid cases; invalid = no post file, processing must fail)."""
    from lodestar_tpu.state_transition.altair import (
        process_attestation_altair,
        process_sync_aggregate,
    )

    spe = MINIMAL.SLOTS_PER_EPOCH
    # attestation: from a post-fork block
    for slot in range(spe + 2, 4 * spe):
        blocks = canonical_blocks(dev_a, slot, slot)
        if not blocks or not len(blocks[0].message.body.attestations):
            continue
        blk = blocks[0]
        pre = _state_at(dev_a, MINIMAL, CFG_ALTAIR, slot)
        att = blk.message.body.attestations[0]
        post = clone_state(MINIMAL, pre)
        ctx = EpochContext.create_from_state(MINIMAL, post)
        process_attestation_altair(MINIMAL, CFG_ALTAIR, ctx, post, att, False)
        d = case_dir("altair", "operations", "attestation", "pyspec_tests", "from_block")
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre))
        write_ssz(d, "attestation", T.phase0.Attestation.serialize(att))
        write_ssz(d, "post", state_bytes_p(MINIMAL, "altair", post))

        # invalid: future-slot attestation (inclusion-delay violation)
        bad = T.phase0.Attestation.deserialize(T.phase0.Attestation.serialize(att))
        bad.data.slot = pre.slot
        d = case_dir(
            "altair", "operations", "attestation", "pyspec_tests", "invalid_future_slot"
        )
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre))
        write_ssz(d, "attestation", T.phase0.Attestation.serialize(bad))
        break

    # sync_aggregate: from a post-fork block, applied at the block's slot
    for slot in range(spe + 2, 3 * spe):
        blocks = canonical_blocks(dev_a, slot, slot)
        if not blocks:
            continue
        blk = blocks[0]
        agg = blk.message.body.sync_aggregate
        if not any(agg.sync_committee_bits):
            continue
        parent_state = clone_state(
            MINIMAL,
            dev_a.chain.get_state_by_block_root(bytes(blk.message.parent_root)),
        )
        ctx = process_slots(MINIMAL, CFG_ALTAIR, parent_state, slot)
        pre = clone_state(MINIMAL, parent_state)
        post = clone_state(MINIMAL, pre)
        # signature-checked: the vector pins the verifying path
        process_sync_aggregate(MINIMAL, CFG_ALTAIR, ctx, post, agg, True)
        d = case_dir("altair", "operations", "sync_aggregate", "pyspec_tests", "from_block")
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre))
        write_ssz(d, "sync_aggregate", T.altair.SyncAggregate.serialize(agg))
        write_ssz(d, "post", state_bytes_p(MINIMAL, "altair", post))

        # invalid: empty participation with a non-infinity signature
        bad = T.altair.SyncAggregate.deserialize(T.altair.SyncAggregate.serialize(agg))
        bad.sync_committee_bits = [False] * len(list(agg.sync_committee_bits))
        d = case_dir(
            "altair", "operations", "sync_aggregate", "pyspec_tests",
            "invalid_empty_with_signature",
        )
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre))
        write_ssz(d, "sync_aggregate", T.altair.SyncAggregate.serialize(bad))
        break


# ============================== bellatrix ===================================


def gen_bellatrix(dev_b: DevChain) -> None:
    """bellatrix fork/fork, transition/core, sanity/blocks,
    epoch_processing, operations/execution_payload (+ attestation)."""
    from lodestar_tpu.state_transition.upgrade import upgrade_state_to_bellatrix

    spe = MINIMAL.SLOTS_PER_EPOCH
    fork_slot = 2 * spe  # BELLATRIX_FORK_EPOCH = 2

    # fork/fork: pure upgrade on the boundary state (advance under a
    # config that does NOT apply bellatrix automatically)
    pre_root = dev_b.chain.fork_choice.proto.get_ancestor(
        dev_b.chain.head_root, fork_slot - 1
    )
    pre_state = clone_state(MINIMAL, dev_b.chain.get_state_by_block_root(pre_root))
    process_slots(MINIMAL, CFG_ALTAIR, pre_state, fork_slot)
    pre = clone_state(MINIMAL, pre_state)
    upgrade_state_to_bellatrix(MINIMAL, CFG_BELLA, pre_state)
    d = case_dir("bellatrix", "fork", "fork", "pyspec_tests", "epoch2_upgrade")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", pre))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", pre_state))
    write_yaml(d, "meta", {"fork": "bellatrix"})

    # transition/core: blocks crossing the bellatrix activation epoch
    t_pre = _state_at(dev_b, MINIMAL, CFG_BELLA, fork_slot - spe)
    blocks = canonical_blocks(dev_b, fork_slot - spe + 1, fork_slot + spe)
    post_t = clone_state(MINIMAL, t_pre)
    for b in blocks:
        post_t, _ = state_transition(
            MINIMAL, CFG_BELLA, post_t, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    d = case_dir("bellatrix", "transition", "core", "pyspec_tests", "through_bellatrix_fork")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "altair", t_pre))
    for i, b in enumerate(blocks):
        fork = "altair" if b.message.slot < fork_slot else "bellatrix"
        write_ssz(d, f"blocks_{i}", block_bytes_p(MINIMAL, fork, b))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", post_t))
    write_yaml(d, "meta", {
        "post_fork": "bellatrix", "fork_epoch": 2, "blocks_count": len(blocks),
    })

    # sanity/blocks: two post-fork (pre-merge, default-payload) blocks
    s_pre = _state_at(dev_b, MINIMAL, CFG_BELLA, fork_slot + 2)
    s_blocks = canonical_blocks(dev_b, fork_slot + 3, fork_slot + 4)
    s_post = clone_state(MINIMAL, s_pre)
    for b in s_blocks:
        s_post, _ = state_transition(
            MINIMAL, CFG_BELLA, s_post, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    d = case_dir("bellatrix", "sanity", "blocks", "pyspec_tests", "two_blocks")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", s_pre))
    for i, b in enumerate(s_blocks):
        write_ssz(d, f"blocks_{i}", block_bytes_p(MINIMAL, "bellatrix", b))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", s_post))
    write_yaml(d, "meta", {"blocks_count": len(s_blocks)})

    # sanity/slots on a bellatrix state
    sl_pre = _state_at(dev_b, MINIMAL, CFG_BELLA, fork_slot + spe - 2)
    sl_post = clone_state(MINIMAL, sl_pre)
    process_slots(MINIMAL, CFG_BELLA, sl_post, sl_post.slot + spe)
    d = case_dir("bellatrix", "sanity", "slots", "pyspec_tests", "over_epoch_boundary")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", sl_pre))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", sl_post))
    write_yaml(d, "slots", spe)

    # epoch_processing: the altair handler set on a bellatrix state
    from gen_spec_vectors import _altair_epoch_fns

    base = _state_at(dev_b, MINIMAL, CFG_BELLA, 4 * spe - 1)
    current_epoch = (4 * spe - 1) // spe
    v = base.validators[5]
    v.slashed = True
    v.withdrawable_epoch = current_epoch + MINIMAL.EPOCHS_PER_SLASHINGS_VECTOR // 2
    base.slashings[current_epoch % MINIMAL.EPOCHS_PER_SLASHINGS_VECTOR] = (
        v.effective_balance
    )
    scores = list(base.inactivity_scores)
    scores[2] = 9
    base.inactivity_scores = scores
    for handler, fn in _altair_epoch_fns().items():
        pre_e = clone_state(MINIMAL, base)
        post_e = clone_state(MINIMAL, pre_e)
        fn(post_e)
        d = case_dir("bellatrix", "epoch_processing", handler, "pyspec_tests", "mid_chain")
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", pre_e))
        write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", post_e))

    # operations/execution_payload: the merge-transition payload applied to
    # a pre-merge state (official format: body + execution.yaml)
    gen_execution_payload_ops(dev_b)

    # operations/attestation on a bellatrix state
    from lodestar_tpu.state_transition.altair import process_attestation_altair

    for slot in range(fork_slot + 2, 4 * spe):
        blks = canonical_blocks(dev_b, slot, slot)
        if not blks or not len(blks[0].message.body.attestations):
            continue
        att = blks[0].message.body.attestations[0]
        a_pre = _state_at(dev_b, MINIMAL, CFG_BELLA, slot)
        a_post = clone_state(MINIMAL, a_pre)
        ctx = EpochContext.create_from_state(MINIMAL, a_post)
        process_attestation_altair(MINIMAL, CFG_BELLA, ctx, a_post, att, False)
        d = case_dir("bellatrix", "operations", "attestation", "pyspec_tests", "from_block")
        write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", a_pre))
        write_ssz(d, "attestation", T.phase0.Attestation.serialize(att))
        write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", a_post))
        break


def gen_execution_payload_ops(dev_b: DevChain) -> None:
    """operations/execution_payload: valid merge payload, stale prev_randao,
    and engine-rejected (execution_valid: false) cases.  The official shape
    carries the whole body + execution.yaml (presets/operations.ts)."""
    import hashlib

    from lodestar_tpu.state_transition.bellatrix import (
        compute_timestamp_at_slot,
        process_execution_payload,
    )
    from lodestar_tpu.state_transition.misc import get_randao_mix

    spe = MINIMAL.SLOTS_PER_EPOCH
    slot = 2 * spe + 3
    pre = _state_at(dev_b, MINIMAL, CFG_BELLA, slot)
    epoch = compute_epoch_at_slot(MINIMAL, pre.slot)
    tb = _types(MINIMAL).bellatrix

    def make_payload(**overrides) -> Fields:
        fields = dict(
            parent_hash=b"\x21" * 32,
            fee_recipient=b"\x00" * 20,
            state_root=b"\x31" * 32,
            receipts_root=b"\x41" * 32,
            logs_bloom=b"\x00" * MINIMAL.BYTES_PER_LOGS_BLOOM,
            prev_randao=bytes(get_randao_mix(MINIMAL, pre, epoch)),
            block_number=1,
            gas_limit=30_000_000,
            gas_used=21_000,
            timestamp=compute_timestamp_at_slot(MINIMAL, CFG_BELLA, pre, pre.slot),
            extra_data=b"",
            base_fee_per_gas=7,
            block_hash=b"",  # filled below
            transactions=[b"\x02" + b"\x00" * 10],
        )
        fields.update(overrides)
        pl = Fields(**fields)
        if not pl.block_hash:
            pl.block_hash = hashlib.sha256(
                b"exec-block:" + bytes(pl.parent_hash) + pl.block_number.to_bytes(8, "little")
            ).digest()
        return pl

    def body_with(payload) -> Fields:
        body = tb.BeaconBlockBody.default()
        body.execution_payload = payload
        return body

    # valid merge-transition payload (pre-merge state ignores parent_hash)
    payload = make_payload()
    post = clone_state(MINIMAL, pre)
    process_execution_payload(MINIMAL, CFG_BELLA, post, body_with(payload), None)
    d = case_dir(
        "bellatrix", "operations", "execution_payload", "pyspec_tests", "merge_block"
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", pre))
    write_ssz(d, "body", tb.BeaconBlockBody.serialize(body_with(payload)))
    write_yaml(d, "execution", {"execution_valid": True})
    write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", post))

    # second payload on the now-merged state: parent_hash must chain
    pre2 = post
    epoch2 = compute_epoch_at_slot(MINIMAL, pre2.slot)
    payload2 = make_payload(
        parent_hash=bytes(pre2.latest_execution_payload_header.block_hash),
        block_number=2,
        prev_randao=bytes(get_randao_mix(MINIMAL, pre2, epoch2)),
    )
    post2 = clone_state(MINIMAL, pre2)
    process_execution_payload(MINIMAL, CFG_BELLA, post2, body_with(payload2), None)
    d = case_dir(
        "bellatrix", "operations", "execution_payload", "pyspec_tests", "chained_payload"
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", pre2))
    write_ssz(d, "body", tb.BeaconBlockBody.serialize(body_with(payload2)))
    write_yaml(d, "execution", {"execution_valid": True})
    write_ssz(d, "post", state_bytes_p(MINIMAL, "bellatrix", post2))

    # invalid: wrong parent hash on a merged state
    bad_parent = make_payload(parent_hash=b"\x66" * 32, block_number=2,
                              prev_randao=bytes(get_randao_mix(MINIMAL, pre2, epoch2)))
    d = case_dir(
        "bellatrix", "operations", "execution_payload", "pyspec_tests",
        "invalid_parent_hash",
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", pre2))
    write_ssz(d, "body", tb.BeaconBlockBody.serialize(body_with(bad_parent)))
    write_yaml(d, "execution", {"execution_valid": True})

    # invalid: stale prev_randao
    bad_randao = make_payload(prev_randao=b"\x13" * 32)
    d = case_dir(
        "bellatrix", "operations", "execution_payload", "pyspec_tests",
        "invalid_prev_randao",
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", pre))
    write_ssz(d, "body", tb.BeaconBlockBody.serialize(body_with(bad_randao)))
    write_yaml(d, "execution", {"execution_valid": True})

    # invalid: engine verdict false on an otherwise-valid payload
    d = case_dir(
        "bellatrix", "operations", "execution_payload", "pyspec_tests",
        "invalid_engine_verdict",
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "bellatrix", pre))
    write_ssz(d, "body", tb.BeaconBlockBody.serialize(body_with(payload)))
    write_yaml(d, "execution", {"execution_valid": False})


# ====================== phase0 operation coverage ===========================


def gen_phase0_operations_full(dev: DevChain) -> None:
    """proposer_slashing / attester_slashing / voluntary_exit / deposit
    vectors, each with a valid and an invalid case (invalid = no post file).
    Signatures are REAL (interop keys) and verified by the runner."""
    from lodestar_tpu.spec_test_util.deposits import build_deposits, deposit_proof
    from lodestar_tpu.state_transition.block import (
        process_attester_slashing,
        process_deposit,
        process_proposer_slashing,
        process_voluntary_exit,
    )

    spe = MINIMAL.SLOTS_PER_EPOCH
    pre = _state_at(dev, MINIMAL, CFG, 2 * spe + 1)
    ctx = EpochContext.create_from_state(MINIMAL, pre)
    epoch = compute_epoch_at_slot(MINIMAL, pre.slot)

    # -- proposer_slashing: one proposer, two conflicting headers ----------
    proposer = 3
    domain = get_domain(MINIMAL, pre, DOMAIN_BEACON_PROPOSER, epoch)
    sk = interop_secret_key(proposer)

    def header(body_root: bytes) -> Fields:
        return Fields(
            slot=pre.slot, proposer_index=proposer,
            parent_root=b"\x01" * 32, state_root=b"\x02" * 32,
            body_root=body_root,
        )

    def sign_header(h) -> Fields:
        root = compute_signing_root(MINIMAL, T.phase0.BeaconBlockHeader, h, domain)
        return Fields(message=h, signature=sk.sign(root).to_bytes())

    slashing = Fields(
        signed_header_1=sign_header(header(b"\xaa" * 32)),
        signed_header_2=sign_header(header(b"\xbb" * 32)),
    )
    post = clone_state(MINIMAL, pre)
    process_proposer_slashing(MINIMAL, CFG, ctx, post, slashing, True)
    d = case_dir("phase0", "operations", "proposer_slashing", "pyspec_tests", "double_header")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", pre))
    write_ssz(d, "proposer_slashing", T.phase0.ProposerSlashing.serialize(slashing))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "phase0", post))

    # invalid: identical headers
    same = sign_header(header(b"\xaa" * 32))
    bad = Fields(signed_header_1=same, signed_header_2=same)
    d = case_dir(
        "phase0", "operations", "proposer_slashing", "pyspec_tests",
        "invalid_identical_headers",
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", pre))
    write_ssz(d, "proposer_slashing", T.phase0.ProposerSlashing.serialize(bad))

    # -- attester_slashing: double vote by an overlapping committee --------
    att_domain = get_domain(MINIMAL, pre, DOMAIN_BEACON_ATTESTER, epoch)
    indices = [1, 2, 4]

    def indexed(block_root: bytes) -> Fields:
        data = Fields(
            slot=pre.slot - 1, index=0,
            beacon_block_root=block_root,
            source=Fields(
                epoch=pre.current_justified_checkpoint.epoch,
                root=bytes(pre.current_justified_checkpoint.root),
            ),
            target=Fields(epoch=epoch, root=b"\x0e" * 32),
        )
        root = compute_signing_root(MINIMAL, T.phase0.AttestationData, data, att_domain)
        from lodestar_tpu.crypto.bls.api import sign_aggregate

        sig = sign_aggregate([interop_secret_key(i) for i in indices], root)
        return Fields(
            attesting_indices=indices, data=data, signature=sig.to_bytes()
        )

    a_slashing = Fields(
        attestation_1=indexed(b"\xcc" * 32), attestation_2=indexed(b"\xdd" * 32)
    )
    post = clone_state(MINIMAL, pre)
    a_ctx = EpochContext.create_from_state(MINIMAL, post)
    process_attester_slashing(MINIMAL, CFG, a_ctx, post, a_slashing, True)
    d = case_dir("phase0", "operations", "attester_slashing", "pyspec_tests", "double_vote")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", pre))
    write_ssz(d, "attester_slashing", T.phase0.AttesterSlashing.serialize(a_slashing))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "phase0", post))

    # invalid: same attestation twice (data not slashable)
    one = indexed(b"\xcc" * 32)
    bad_a = Fields(attestation_1=one, attestation_2=one)
    d = case_dir(
        "phase0", "operations", "attester_slashing", "pyspec_tests",
        "invalid_same_data",
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", pre))
    write_ssz(d, "attester_slashing", T.phase0.AttesterSlashing.serialize(bad_a))

    # -- voluntary_exit ----------------------------------------------------
    exit_index = 7
    exit_msg = Fields(epoch=epoch, validator_index=exit_index)
    v_domain = get_domain(MINIMAL, pre, DOMAIN_VOLUNTARY_EXIT, epoch)
    root = compute_signing_root(MINIMAL, T.phase0.VoluntaryExit, exit_msg, v_domain)
    signed_exit = Fields(
        message=exit_msg,
        signature=interop_secret_key(exit_index).sign(root).to_bytes(),
    )
    post = clone_state(MINIMAL, pre)
    process_voluntary_exit(MINIMAL, CFG, ctx, post, signed_exit, True)
    d = case_dir("phase0", "operations", "voluntary_exit", "pyspec_tests", "success_exit")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", pre))
    write_ssz(d, "voluntary_exit", T.phase0.SignedVoluntaryExit.serialize(signed_exit))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "phase0", post))

    # invalid: exit dated in the future
    future = Fields(epoch=epoch + 3, validator_index=exit_index)
    froot = compute_signing_root(MINIMAL, T.phase0.VoluntaryExit, future, v_domain)
    bad_exit = Fields(
        message=future, signature=interop_secret_key(exit_index).sign(froot).to_bytes()
    )
    d = case_dir(
        "phase0", "operations", "voluntary_exit", "pyspec_tests", "invalid_future_epoch"
    )
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", pre))
    write_ssz(d, "voluntary_exit", T.phase0.SignedVoluntaryExit.serialize(bad_exit))

    # -- deposit: a 17th validator joins ----------------------------------
    deposits = build_deposits(MINIMAL, CFG, 17)
    leaves = [
        T.phase0.DepositData.hash_tree_root(dep.data) for dep in deposits
    ]
    dep_pre = clone_state(MINIMAL, pre)
    import hashlib as _hl

    # root over the padded depth-32 tree with the length mix-in
    layer = list(leaves)
    from lodestar_tpu.ssz.core import ZERO_HASHES
    from lodestar_tpu.params.presets import DEPOSIT_CONTRACT_TREE_DEPTH

    for depth in range(DEPOSIT_CONTRACT_TREE_DEPTH):
        nxt = []
        for i in range(0, len(layer), 2):
            left = layer[i]
            right = layer[i + 1] if i + 1 < len(layer) else ZERO_HASHES[depth]
            nxt.append(_hl.sha256(left + right).digest())
        layer = nxt or [ZERO_HASHES[depth + 1]]
    tree_root = _hl.sha256(layer[0] + (17).to_bytes(32, "little")).digest()
    dep_pre.eth1_data = Fields(
        deposit_root=tree_root, deposit_count=17, block_hash=b"\x12" * 32
    )
    dep_pre.eth1_deposit_index = 16
    dep = deposits[16]
    post = clone_state(MINIMAL, dep_pre)
    d_ctx = EpochContext.create_from_state(MINIMAL, post)
    process_deposit(MINIMAL, CFG, d_ctx, post, dep)
    assert len(post.validators) == 17, "deposit vector must add a validator"
    d = case_dir("phase0", "operations", "deposit", "pyspec_tests", "new_validator")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", dep_pre))
    write_ssz(d, "deposit", T.phase0.Deposit.serialize(dep))
    write_ssz(d, "post", state_bytes_p(MINIMAL, "phase0", post))

    # invalid: proof for the wrong leaf index
    wrong = Fields(
        proof=deposit_proof(leaves, 3, 17), data=dep.data
    )
    d = case_dir("phase0", "operations", "deposit", "pyspec_tests", "invalid_proof")
    write_ssz(d, "pre", state_bytes_p(MINIMAL, "phase0", dep_pre))
    write_ssz(d, "deposit", T.phase0.Deposit.serialize(wrong))


# =========================== ssz_static breadth =============================


def gen_ssz_static_full(dev, dev_a, dev_b) -> None:
    """>=5 cases per type across the three forks + a corrupt-encoding suite
    (serialized payloads that MUST fail deserialization — each verified to
    fail at generation time)."""
    from lodestar_tpu.utils.snappy import frame_compress

    state0 = dev.chain.head_state()
    state_a = dev_a.chain.head_state()
    state_b = dev_b.chain.head_state()

    def emit_cases(fork: str, name: str, typ, values) -> None:
        for i, value in enumerate(values):
            d = case_dir(fork, "ssz_static", name, "ssz_random", f"case_{i}")
            ser = typ.serialize(value)
            write_ssz(d, "serialized", ser)
            write_yaml(d, "roots", {"root": "0x" + typ.hash_tree_root(value).hex()})

    def checkpoints(state):
        return [
            state.finalized_checkpoint,
            state.current_justified_checkpoint,
            state.previous_justified_checkpoint,
            Fields(epoch=0, root=b"\x00" * 32),
            Fields(epoch=2**64 - 1, root=b"\xff" * 32),
        ]

    emit_cases("phase0", "Checkpoint", T.phase0.Checkpoint, checkpoints(state0))
    emit_cases(
        "phase0", "Validator", T.phase0.Validator,
        [state0.validators[i] for i in range(4)] + [
            Fields(
                pubkey=b"\xab" * 48, withdrawal_credentials=b"\x00" * 32,
                effective_balance=0, slashed=True,
                activation_eligibility_epoch=2**64 - 1,
                activation_epoch=2**64 - 1, exit_epoch=2**64 - 1,
                withdrawable_epoch=2**64 - 1,
            )
        ],
    )
    emit_cases(
        "phase0", "Fork", T.phase0.Fork,
        [
            state0.fork, state_a.fork,
            Fields(previous_version=b"\x00" * 4, current_version=b"\xff" * 4, epoch=0),
            Fields(previous_version=b"\x01\x02\x03\x04",
                   current_version=b"\x05\x06\x07\x08", epoch=77),
            Fields(previous_version=b"\xaa" * 4, current_version=b"\xbb" * 4,
                   epoch=2**64 - 1),
        ],
    )
    headers = [
        state0.latest_block_header, state_a.latest_block_header,
        state_b.latest_block_header,
        Fields(slot=0, proposer_index=0, parent_root=b"\x00" * 32,
               state_root=b"\x00" * 32, body_root=b"\x00" * 32),
        Fields(slot=2**63, proposer_index=2**40, parent_root=b"\x11" * 32,
               state_root=b"\x22" * 32, body_root=b"\x33" * 32),
    ]
    emit_cases("phase0", "BeaconBlockHeader", T.phase0.BeaconBlockHeader, headers)
    atts = list(state0.previous_epoch_attestations)[:3]
    att_data = [a.data for a in atts] + [
        Fields(slot=0, index=0, beacon_block_root=b"\x00" * 32,
               source=Fields(epoch=0, root=b"\x00" * 32),
               target=Fields(epoch=0, root=b"\x00" * 32)),
        Fields(slot=12345, index=63, beacon_block_root=b"\x77" * 32,
               source=Fields(epoch=11, root=b"\x88" * 32),
               target=Fields(epoch=12, root=b"\x99" * 32)),
    ]
    emit_cases("phase0", "AttestationData", T.phase0.AttestationData, att_data)
    emit_cases(
        "phase0", "Eth1Data", T.phase0.Eth1Data,
        [
            state0.eth1_data, state_a.eth1_data,
            Fields(deposit_root=b"\x00" * 32, deposit_count=0, block_hash=b"\x00" * 32),
            Fields(deposit_root=b"\xab" * 32, deposit_count=2**64 - 1,
                   block_hash=b"\xcd" * 32),
            Fields(deposit_root=b"\x10" * 32, deposit_count=17, block_hash=b"\x12" * 32),
        ],
    )
    # one full BeaconState per fork (the heavyweight case)
    emit_cases("phase0", "BeaconState", T.phase0.BeaconState, [state0])
    emit_cases("altair", "BeaconState", T.altair.BeaconState, [state_a])
    emit_cases("bellatrix", "BeaconState", T.bellatrix.BeaconState, [state_b])
    emit_cases(
        "altair", "SyncCommittee", T.altair.SyncCommittee,
        [state_a.current_sync_committee, state_a.next_sync_committee,
         state_b.current_sync_committee],
    )
    # signed blocks (variable-size containers with nested payloads)
    blocks0 = canonical_blocks(dev, 1, 5)
    emit_cases("phase0", "SignedBeaconBlock", T.phase0.SignedBeaconBlock, blocks0)
    spe = MINIMAL.SLOTS_PER_EPOCH
    blocks_b = canonical_blocks(dev_b, 2 * spe + 1, 2 * spe + 3)
    emit_cases("bellatrix", "SignedBeaconBlock", T.bellatrix.SignedBeaconBlock, blocks_b)
    emit_cases(
        "bellatrix", "ExecutionPayloadHeader", T.bellatrix.ExecutionPayloadHeader,
        [state_b.latest_execution_payload_header],
    )

    # -- corrupt encodings: must FAIL deserialization ----------------------
    corrupt_specs = []
    ck = T.phase0.Checkpoint.serialize(state0.finalized_checkpoint)
    corrupt_specs.append(("phase0", "Checkpoint", T.phase0.Checkpoint, ck[:-1], "truncated"))
    corrupt_specs.append(("phase0", "Checkpoint", T.phase0.Checkpoint, ck + b"\x00", "trailing_byte"))
    blk = T.phase0.SignedBeaconBlock.serialize(blocks0[0])
    corrupt_specs.append(
        ("phase0", "SignedBeaconBlock", T.phase0.SignedBeaconBlock, blk[:40], "truncated")
    )
    # bad variable-offset: SignedBeaconBlock's fixed part is [offset(message),
    # signature]; point the message offset past the end of the buffer
    bad_off = bytearray(blk)
    bad_off[0:4] = (len(blk) + 1000).to_bytes(4, "little")
    corrupt_specs.append(
        ("phase0", "SignedBeaconBlock", T.phase0.SignedBeaconBlock, bytes(bad_off), "bad_offset")
    )
    st_ser = T.phase0.BeaconState.serialize(state0)
    corrupt_specs.append(
        ("phase0", "BeaconState", T.phase0.BeaconState, st_ser[: len(st_ser) // 2], "truncated")
    )
    for fork, name, typ, payload, label in corrupt_specs:
        try:
            typ.deserialize(payload)
        except Exception:
            d = case_dir(fork, "ssz_static", name, "ssz_invalid", f"invalid_{label}")
            with open(os.path.join(d, "serialized.ssz_snappy"), "wb") as f:
                f.write(frame_compress(payload))
        else:  # pragma: no cover - generation-time guard
            raise AssertionError(
                f"corrupt {name} payload ({label}) unexpectedly deserialized"
            )


# ============================== mainnet tree ================================


async def gen_mainnet() -> None:
    """A mainnet-PRESET tree (64-validator interop chain): sanity, finality,
    epoch_processing, rewards, shuffling, ssz_static.  Pins the preset-
    dependent constants (32-slot epochs, 90-round shuffle, mainnet
    committee math) the minimal tree cannot."""
    spe = MAINNET.SLOTS_PER_EPOCH
    dev = await build_chain_p(MAINNET, CFG_MAINNET, 4 * spe + 2, n_validators=64)
    assert dev.chain.fork_choice.store.finalized_checkpoint.epoch >= 1

    def mcase(fork, runner, handler, suite, name):
        return case_dir(fork, runner, handler, suite, name, config="mainnet")

    # sanity/blocks
    pre = _state_at(dev, MAINNET, CFG_MAINNET, 2)
    blocks = canonical_blocks(dev, 3, 4)
    post = clone_state(MAINNET, pre)
    for b in blocks:
        post, _ = state_transition(
            MAINNET, CFG_MAINNET, post, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    d = mcase("phase0", "sanity", "blocks", "pyspec_tests", "two_blocks")
    write_ssz(d, "pre", state_bytes_p(MAINNET, "phase0", pre))
    for i, b in enumerate(blocks):
        write_ssz(d, f"blocks_{i}", block_bytes_p(MAINNET, "phase0", b))
    write_ssz(d, "post", state_bytes_p(MAINNET, "phase0", post))
    write_yaml(d, "meta", {"blocks_count": len(blocks)})

    # sanity/slots across an epoch boundary
    pre2 = _state_at(dev, MAINNET, CFG_MAINNET, spe - 2)
    post2 = clone_state(MAINNET, pre2)
    process_slots(MAINNET, CFG_MAINNET, post2, post2.slot + 4)
    d = mcase("phase0", "sanity", "slots", "pyspec_tests", "over_epoch_boundary")
    write_ssz(d, "pre", state_bytes_p(MAINNET, "phase0", pre2))
    write_ssz(d, "post", state_bytes_p(MAINNET, "phase0", post2))
    write_yaml(d, "slots", 4)

    # finality: two full epochs
    pre3 = _state_at(dev, MAINNET, CFG_MAINNET, 2 * spe)
    blocks3 = canonical_blocks(dev, 2 * spe + 1, 4 * spe)
    post3 = clone_state(MAINNET, pre3)
    for b in blocks3:
        post3, _ = state_transition(
            MAINNET, CFG_MAINNET, post3, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    assert post3.finalized_checkpoint.epoch > pre3.finalized_checkpoint.epoch
    d = mcase("phase0", "finality", "finality", "pyspec_tests", "two_epochs_finalize")
    write_ssz(d, "pre", state_bytes_p(MAINNET, "phase0", pre3))
    for i, b in enumerate(blocks3):
        write_ssz(d, f"blocks_{i}", block_bytes_p(MAINNET, "phase0", b))
    write_ssz(d, "post", state_bytes_p(MAINNET, "phase0", post3))
    write_yaml(d, "meta", {"blocks_count": len(blocks3)})

    # epoch_processing on a mid-chain mainnet state
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        process_effective_balance_updates,
        process_justification_and_finalization,
        process_rewards_and_penalties,
        process_registry_updates,
        process_slashings,
    )

    base = _state_at(dev, MAINNET, CFG_MAINNET, 3 * spe - 1)
    fns = {
        "justification_and_finalization": lambda st, fl: process_justification_and_finalization(MAINNET, st, fl),
        "rewards_and_penalties": lambda st, fl: process_rewards_and_penalties(MAINNET, CFG_MAINNET, st, fl),
        "registry_updates": lambda st, fl: process_registry_updates(MAINNET, CFG_MAINNET, st),
        "slashings": lambda st, fl: process_slashings(MAINNET, st, fl),
        "effective_balance_updates": lambda st, fl: process_effective_balance_updates(MAINNET, st),
    }
    for handler, fn in fns.items():
        pre_e = clone_state(MAINNET, base)
        post_e = clone_state(MAINNET, pre_e)
        pctx = EpochContext.create_from_state(MAINNET, post_e)
        flags = before_process_epoch(MAINNET, pctx, post_e)
        fn(post_e, flags)
        d = mcase("phase0", "epoch_processing", handler, "pyspec_tests", "mid_chain")
        write_ssz(d, "pre", state_bytes_p(MAINNET, "phase0", pre_e))
        write_ssz(d, "post", state_bytes_p(MAINNET, "phase0", post_e))

    # rewards/basic
    from lodestar_tpu.state_transition.epoch import get_attestation_component_deltas
    from lodestar_tpu.ssz import Container, List as SszList, uint64

    dt = Container(
        "Deltas",
        [
            ("rewards", SszList(uint64, MAINNET.VALIDATOR_REGISTRY_LIMIT)),
            ("penalties", SszList(uint64, MAINNET.VALIDATOR_REGISTRY_LIMIT)),
        ],
    )
    rctx = EpochContext.create_from_state(MAINNET, base)
    rflags = before_process_epoch(MAINNET, rctx, base)
    components = get_attestation_component_deltas(MAINNET, CFG_MAINNET, base, rflags)
    d = mcase("phase0", "rewards", "basic", "pyspec_tests", "mid_chain")
    write_ssz(d, "pre", state_bytes_p(MAINNET, "phase0", base))
    for key, stem in {
        "source": "source_deltas", "target": "target_deltas",
        "head": "head_deltas", "inclusion_delay": "inclusion_delay_deltas",
        "inactivity": "inactivity_penalty_deltas",
    }.items():
        rewards, penalties = components[key]
        write_ssz(d, stem, dt.serialize(Fields(
            rewards=[int(x) for x in rewards],
            penalties=[int(x) for x in penalties],
        )))

    # shuffling with the mainnet round count
    import numpy as np

    from lodestar_tpu.state_transition.shuffle import unshuffle_list

    seed = bytes(reversed(range(32)))
    for count in (5, 33, 128):
        shuffled = unshuffle_list(
            np.arange(count, dtype=np.int64), seed, MAINNET.SHUFFLE_ROUND_COUNT
        )
        d = mcase("phase0", "shuffling", "core", "shuffle",
                  f"shuffle_0x{seed[:4].hex()}_{count}")
        write_yaml(d, "mapping", {
            "seed": "0x" + seed.hex(), "count": count,
            "mapping": [int(x) for x in shuffled],
        })

    # ssz_static: the mainnet-preset BeaconState + core types
    state = dev.chain.head_state()
    for name, typ, value in (
        ("BeaconState", TM.phase0.BeaconState, state),
        ("Checkpoint", TM.phase0.Checkpoint, state.finalized_checkpoint),
        ("Validator", TM.phase0.Validator, state.validators[0]),
        ("BeaconBlockHeader", TM.phase0.BeaconBlockHeader, state.latest_block_header),
    ):
        d = mcase("phase0", "ssz_static", name, "ssz_random", "case_0")
        write_ssz(d, "serialized", typ.serialize(value))
        write_yaml(d, "roots", {"root": "0x" + typ.hash_tree_root(value).hex()})

    dev.chain.bls.close()


async def generate(dev, dev_a) -> None:
    """Entry called from gen_spec_vectors.main with the shared phase0 and
    altair chains; builds the bellatrix chain itself."""
    spe = MINIMAL.SLOTS_PER_EPOCH
    gen_altair_sanity_finality(dev_a)
    gen_altair_rewards(dev_a)
    gen_altair_operations(dev_a)
    gen_phase0_operations_full(dev)
    dev_b = await build_chain_p(MINIMAL, CFG_BELLA, 4 * spe + 2)
    gen_bellatrix(dev_b)
    gen_ssz_static_full(dev, dev_a, dev_b)
    await gen_mainnet()
    dev_b.chain.bls.close()
