#!/usr/bin/env python3
"""Prewarm farm: populate the durable AOT executable store out-of-band.

ROADMAP item 4's production contract: a fleet doing rolling restarts
never compiles — THIS tool pays the compile once per topology, ahead of
time, and the nodes restart with ``--bls-warmup-load-only`` against the
populated store (docs/aot.md has the runbook).

What one run does:

- takes the farm-level single-writer lockfile (``prewarm.lock`` in the
  store) so concurrent prewarmers on a shared store don't stampede the
  same compiles — a held lock means another farm is already working:
  this one exits 3 immediately (rerun later, or point at its own store);
- builds a ``TpuBlsVerifier`` over the requested device ordinals and
  runs its ``warmup()``, which walks memo -> AOT store -> persistent
  cache -> compile per (bucket, ordinal) and persists every freshly
  materialized executable back into the store (per-ordinal fan-out: one
  serialized executable per device, exactly like the ``jit(device=d)``
  programs they replace);
- reports per-entry outcomes plus the store's hit/miss/save counters.

``--verify`` instead runs the integrity sweep: every manifest entry's
checksum + jax/ops fingerprint, plus orphan temp files from crashed
writers (exit 1 on any corrupt entry, after listing them).

Usage:
    python tools/prewarm.py --store .aot_store --buckets 4,16 --devices 0
    python tools/prewarm.py --store .aot_store --buckets 128 --devices 0 --mesh
    python tools/prewarm.py --store .aot_store --verify
    python tools/prewarm.py --store .aot_store --verify --sweep-orphans

``--mesh`` builds the round-11 sharded tier's whole-mesh program (ONE
``mesh{k}``-keyed entry per bucket, shared by every restart of the node
that runs that mesh) instead of the per-ordinal fan-out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# XLA:CPU's parallel codegen splits big modules across object files and
# executable serialization keeps only one — a farm compiling on a CPU
# backend MUST pin the split count to 1 or its payloads fail in every
# other process with "Symbols not found" (store.save would refuse them).
# Harmless for TPU backends (the flag only touches CPU codegen; TPU
# executables are device binaries).  Must be set before jax ever loads.
if "jax" not in sys.modules:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_parallel_codegen_split_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_cpu_parallel_codegen_split_count=1"
        ).strip()

#: farm-level lock (distinct from the store's per-save writer lock: the
#: farm holds THIS for its whole run, saves still serialize individually)
FARM_LOCK_NAME = "prewarm.lock"


def prewarm(store_path: str, buckets, n_devices: int = 1,
            fused: Optional[bool] = None, host_final_exp: bool = True,
            lock_wait_s: float = 2.0, mesh: bool = False) -> Dict[str, Any]:
    """Populate ``store_path`` for this host's topology.  Returns the
    report dict; ``{"locked": True}`` when another prewarmer holds the
    farm lock (the caller exits 3 — never a stampede).

    ``mesh=True`` is the round-11 sharded-tier mode: instead of the
    per-ordinal fan-out, it builds the ONE mesh-spanning shard_map
    program per eligible bucket (``warmup_sharded``), stored and
    ledgered under the single ``mesh{k}`` key — the whole fleet's mesh
    program compiles here exactly once, never once per ordinal and
    never once per restart."""
    from lodestar_tpu.aot.store import (
        AotExecutableStore,
        acquire_lockfile,
        release_lockfile,
        topology_tag,
    )
    from lodestar_tpu.chaos import install_from_env

    # chaos activation seam: the campaign's kill-mid-write class arms a
    # plan in THIS process via the env var (a no-op when unset)
    install_from_env()

    os.makedirs(store_path, exist_ok=True)
    farm_lock = os.path.join(store_path, FARM_LOCK_NAME)
    if not acquire_lockfile(farm_lock, lock_wait_s, store=store_path):
        return {"locked": True, "store": store_path, "lock": farm_lock}
    t0 = time.perf_counter()
    try:
        import jax

        from lodestar_tpu.crypto.bls.tpu_verifier import (
            TpuBlsVerifier,
            configure_persistent_cache,
        )

        # the persistent cache stays wired UNDER the store: a prewarm on
        # a box that already has .jax_cache loads warm instead of cold
        configure_persistent_cache()
        store = AotExecutableStore(path=store_path)
        local = jax.devices()
        # mirror cli._make_verifier's ordinal convention EXACTLY: the
        # store keys by executor name, so a prewarm for `--bls-devices N`
        # must produce the same names the node's executors will ask for
        # (1 = the unpinned "default" executor; N/0 = pinned ordinals)
        devices = None if n_devices == 1 else (
            local if n_devices == 0 else local[:n_devices]
        )
        if mesh:
            if devices is None or len(devices) < 2:
                raise SystemExit(
                    "--mesh needs a multi-device pool: pass --devices N "
                    "(>= 2) or 0 (all local devices)"
                )
            eligible = [b for b in buckets if b % len(devices) == 0]
            if not eligible:
                # a silent zero-program "success" would let the operator
                # believe the fleet mesh program is stored when nothing is
                raise SystemExit(
                    f"--mesh: none of buckets {sorted(buckets)} divide "
                    f"evenly across {len(devices)} devices — nothing to "
                    f"prewarm"
                )
            # the mesh program takes any eligible bucket — for a prewarm
            # the requested buckets ARE the eligible set (min = smallest)
            v = TpuBlsVerifier(
                buckets=tuple(buckets), devices=devices,
                fused=fused, host_final_exp=host_final_exp, aot_store=store,
                sharded=True, sharded_min_batch=min(buckets),
            )
            wall = v.warmup_sharded()
            if v.sharded_fallbacks:
                raise SystemExit(
                    f"--mesh: warmup degraded after "
                    f"{len(v._mesh_ex.compiled)} of {len(eligible)} mesh "
                    f"program(s) — the store is NOT fully populated"
                )
        else:
            v = TpuBlsVerifier(
                buckets=tuple(buckets), devices=devices,
                fused=fused, host_final_exp=host_final_exp, aot_store=store,
            )
            wall = v.warmup()
        return {
            "store": store_path,
            "topology": topology_tag(),
            "buckets": list(buckets),
            "devices": (
                [v._mesh_ex.name] if mesh
                else [ex.name for ex in v._executors]
            ),
            "mesh": mesh or None,
            "fused": v.fused,
            "sharded_fallbacks": v.sharded_fallbacks if mesh else None,
            "warmup_s": round(wall, 2),
            "wall_s": round(time.perf_counter() - t0, 2),
            "stats": store.stats(),
            "entries": sorted(store.keys()),
        }
    finally:
        release_lockfile(farm_lock)


def verify(store_path: str, sweep_orphans: bool = False) -> Dict[str, Any]:
    """Integrity sweep of every manifest entry (no devices touched)."""
    from lodestar_tpu.aot.store import AotExecutableStore

    store = AotExecutableStore(path=store_path)
    report = store.verify()
    report["store"] = store_path
    report["entries"] = len(store.keys())
    if sweep_orphans:
        report["orphans_removed"] = store.sweep_orphans()
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--store", default=None,
                    help="store directory (default: $LODESTAR_TPU_AOT_STORE "
                    "or repo-local .aot_store)")
    ap.add_argument("--buckets", default="4,16,64,128,256",
                    help="comma-separated padding buckets to compile")
    ap.add_argument("--devices", type=int, default=1,
                    help="device ordinals to fan out over: 1 = first "
                    "(default), N = first N, 0 = every local device")
    ap.add_argument("--fused", choices=("auto", "on", "off"), default="auto")
    ap.add_argument("--mesh", action="store_true",
                    help="build the ONE mesh-spanning sharded program per "
                    "bucket (stored under the mesh{k} key) instead of the "
                    "per-ordinal fan-out; requires --devices >= 2 or 0")
    ap.add_argument("--host-final-exp", choices=("on", "off"), default="on")
    ap.add_argument("--lock-wait-s", type=float, default=2.0,
                    help="bounded wait for the farm lock before exiting 3")
    ap.add_argument("--verify", action="store_true",
                    help="integrity sweep instead of compiling")
    ap.add_argument("--sweep-orphans", action="store_true",
                    help="with --verify: delete crashed writers' temp files")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    store_path = (
        args.store
        or os.environ.get("LODESTAR_TPU_AOT_STORE")
        or os.path.join(_REPO, ".aot_store")
    )
    if args.verify:
        report = verify(store_path, sweep_orphans=args.sweep_orphans)
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(f"store    {report['store']}  ({report['entries']} entries)")
            for cls in ("ok", "skew", "corrupt", "orphans"):
                for key in report[cls]:
                    print(f"  {cls:8s} {key}")
            if args.sweep_orphans:
                print(f"  orphans removed: {report['orphans_removed']}")
        return 1 if report["corrupt"] else 0

    buckets = tuple(int(b) for b in str(args.buckets).split(",") if b)
    fused = None if args.fused == "auto" else args.fused == "on"
    report = prewarm(
        store_path, buckets, n_devices=args.devices, fused=fused,
        host_final_exp=args.host_final_exp == "on",
        lock_wait_s=args.lock_wait_s, mesh=args.mesh,
    )
    if report.get("locked"):
        print(
            f"another prewarmer holds {report['lock']} — not stampeding "
            f"(rerun when it finishes)",
            file=sys.stderr,
        )
        return 3
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        s = report["stats"]
        print(
            f"prewarmed {report['store']} topology={report['topology']} "
            f"buckets={report['buckets']} devices={report['devices']} "
            f"fused={report['fused']}"
        )
        print(
            f"  warmup {report['warmup_s']}s — saves={s['saves']} "
            f"aot_hits={s['hits']} save_errors={s['save_errors']} "
            f"lock_bypasses={s['lock_bypasses']}"
        )
        for key in report["entries"]:
            print(f"  entry {key}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
