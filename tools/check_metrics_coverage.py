#!/usr/bin/env python3
"""Fail when a metric registered in metrics/registry.py is invisible —
i.e. appears in no Grafana dashboard under dashboards/ and in no doc
under docs/.

A metric nobody can see is dead weight on the exposition AND a broken
promise to the operator; this gate forces every new registry entry to
land with either a dashboard panel or a docs/observability.md table row
(usually both).  Runnable standalone and from tests/test_tracing.py.

Usage:
    python tools/check_metrics_coverage.py [--repo PATH] [--list]

Exit 0 when every metric is covered; exit 1 listing the orphans.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List

# r.counter("name", ...) / r.gauge(...) / r.histogram(...) in registry.py;
# \s* spans the newline argparse-style call wrapping produces
_METRIC_RE = re.compile(r"r\.(?:counter|gauge|histogram)\(\s*\"([^\"]+)\"")


def registered_metrics(repo: str) -> List[str]:
    path = os.path.join(repo, "lodestar_tpu", "metrics", "registry.py")
    with open(path) as f:
        return _METRIC_RE.findall(f.read())


def _corpus(repo: str, subdir: str, exts: tuple) -> Dict[str, str]:
    out: Dict[str, str] = {}
    root = os.path.join(repo, subdir)
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if name.endswith(exts):
            with open(os.path.join(root, name)) as f:
                out[os.path.join(subdir, name)] = f.read()
    return out


def check(repo: str) -> Dict[str, Dict[str, List[str]]]:
    """Per-metric coverage: which dashboards and docs mention it."""
    dashboards = _corpus(repo, "dashboards", (".json",))
    docs = _corpus(repo, "docs", (".md",))
    report: Dict[str, Dict[str, List[str]]] = {}
    for metric in registered_metrics(repo):
        report[metric] = {
            "dashboards": [p for p, text in dashboards.items() if metric in text],
            "docs": [p for p, text in docs.items() if metric in text],
        }
    return report


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--list", action="store_true", help="print full coverage table")
    args = ap.parse_args(argv)
    report = check(args.repo)
    if not report:
        print("no metrics found in registry.py", file=sys.stderr)
        return 1
    orphans = [m for m, cov in report.items() if not cov["dashboards"] and not cov["docs"]]
    if args.list:
        for metric, cov in sorted(report.items()):
            mark = "ORPHAN" if metric in orphans else "ok"
            print(f"{mark:7s} {metric}  dashboards={len(cov['dashboards'])} docs={len(cov['docs'])}")
    for metric in orphans:
        print(
            f"orphan metric: {metric} appears in no dashboards/*.json and no docs/*.md",
            file=sys.stderr,
        )
    if not orphans:
        print(f"metrics coverage OK: {len(report)} metrics all referenced")
    return 1 if orphans else 0


if __name__ == "__main__":
    raise SystemExit(main())
