#!/usr/bin/env python3
"""Fail when a metric registered in metrics/registry.py is invisible —
i.e. appears in no Grafana dashboard under dashboards/ and in no doc
under docs/.

A metric nobody can see is dead weight on the exposition AND a broken
promise to the operator; this gate forces every new registry entry to
land with either a dashboard panel or a docs/observability.md table row
(usually both).  Runnable standalone and from tests/test_tracing.py.

The coverage logic lives in lodestar_tpu.analysis.metrics_coverage (it is
also the lint suite's ``metrics-coverage`` rule — tools/lint.py); this
script is the thin standalone CLI.

Usage:
    python tools/check_metrics_coverage.py [--repo PATH] [--list]

Exit 0 when every metric is covered; exit 1 listing the orphans.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

_REPO_DEFAULT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_DEFAULT)

from lodestar_tpu.analysis.metrics_coverage import (  # noqa: E402
    check,
    registered_metrics,  # noqa: F401  (re-export for existing importers)
)


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=_REPO_DEFAULT)
    ap.add_argument("--list", action="store_true", help="print full coverage table")
    args = ap.parse_args(argv)
    report = check(args.repo)
    if not report:
        print("no metrics found in registry.py", file=sys.stderr)
        return 1
    orphans = [m for m, cov in report.items() if not cov["dashboards"] and not cov["docs"]]
    if args.list:
        for metric, cov in sorted(report.items()):
            mark = "ORPHAN" if metric in orphans else "ok"
            print(f"{mark:7s} {metric}  dashboards={len(cov['dashboards'])} docs={len(cov['docs'])}")
    for metric in orphans:
        print(
            f"orphan metric: {metric} appears in no dashboards/*.json and no docs/*.md",
            file=sys.stderr,
        )
    if not orphans:
        print(f"metrics coverage OK: {len(report)} metrics all referenced")
    return 1 if orphans else 0


if __name__ == "__main__":
    raise SystemExit(main())
