#!/bin/bash
# ASAN/UBSAN run over the native host code (SURVEY section 5.2).
# Usage: tools/sanitize_native.sh   (exits non-zero on any finding)
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p build
echo "== fastbls under address+undefined sanitizers"
cc -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer \
   -o build/fastbls_selftest_asan csrc/fastbls_selftest.c
ASAN_OPTIONS=detect_leaks=1 ./build/fastbls_selftest_asan
echo "== hashtree under address+undefined sanitizers"
cat > build/hashtree_selftest.c <<'EOF'
#include <stdio.h>
#include <string.h>
#include "../csrc/hashtree.c"
int main(void) {
    unsigned char in[64 * 8], out[32 * 8];
    memset(in, 0x5A, sizeof in);
    hashtree_hash_layer((const char *)in, 8, (char *)out);
    hashtree_sha256((const char *)in, sizeof in, (char *)out);
    printf("hashtree sanitizer selftest OK\n");
    return 0;
}
EOF
cc -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer \
   -o build/hashtree_selftest_asan build/hashtree_selftest.c
ASAN_OPTIONS=detect_leaks=1 ./build/hashtree_selftest_asan
echo "sanitizers clean"
