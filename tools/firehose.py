#!/usr/bin/env python3
"""Firehose: sustained-load harness for the BLS verification path.

Replays a configurable, mainnet-shaped duty mix (unaggregated
attestations, aggregates, sync-committee messages, block proposals —
each on its QoS lane) against a real ``BlsBatchPool`` at a target
sets/sec for a sustained window, and reports what the node would feel:

- p50/p99 queue wait (from the ``bls.queue_wait`` spans the pool already
  emits) and p50/p99 end-to-end verify latency, overall and per lane;
- full drop accounting: every offered set ends as verified, typed-dropped
  (``bls_pool_dropped_total{reason,lane}`` analog, read back from
  ``pool.dropped_sets``), shed at intake by backpressure, or errored —
  and the harness asserts nothing is left stranded;
- backpressure behavior: while ``pool.overloaded`` the harness sheds its
  storm-lane submissions exactly as the gossip router does
  (``network/gossip.sheddable_topic``), so an overload run shows intake
  slowing instead of the queue growing without bound.

Every bench stage before this one was a throughput one-shot; this is the
harness that measures the node under SUSTAINED load and proves the
overload machinery (lanes / deadline shedding / eviction / backpressure,
docs/overload.md) actually survives offered load > capacity.

Usage (stub verifier, ~1M-validator storm shape):

    python tools/firehose.py --rate 2000 --seconds 10
    python tools/firehose.py --rate 5000 --seconds 10 --deadline-ms 500
    python tools/firehose.py --verifier native --rate 300 --seconds 5

``bench.py``'s ``firehose`` stage drives ``run_firehose`` in-process to
publish sustained sets/sec at a p99 queue-wait SLO plus an induced
overload run; ``tests/test_overload.py`` runs a seconds-scale smoke.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import random
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from lodestar_tpu import tracing  # noqa: E402
from lodestar_tpu.chain.bls_pool import BlsBatchPool  # noqa: E402
from lodestar_tpu.crypto.bls.verifier import (  # noqa: E402
    SignatureSetPriority,
    VerificationDroppedError,
)

#: duty name -> (lane, sets per job).  The job mix below approximates the
#: gossip traffic of a large validator set: storms of single attestations,
#: a steady aggregate flow (3 sets per aggregate-and-proof job), per-slot
#: sync-committee messages, and the rare block (a block-import job carries
#: a block's worth of sets on the block_proposal lane).
DUTIES: Dict[str, Tuple[SignatureSetPriority, int]] = {
    "unaggregated": (SignatureSetPriority.UNAGGREGATED, 1),
    "aggregate": (SignatureSetPriority.AGGREGATE, 3),
    "sync_committee": (SignatureSetPriority.SYNC_COMMITTEE, 1),
    "block_proposal": (SignatureSetPriority.BLOCK_PROPOSAL, 32),
}

#: default job mix (fractions of JOBS, not sets)
DEFAULT_MIX: Dict[str, float] = {
    "unaggregated": 0.80,
    "aggregate": 0.12,
    "sync_committee": 0.075,
    "block_proposal": 0.005,
}

#: lanes the gossip router sheds at intake under backpressure
#: (mirrors network/gossip.sheddable_topic)
SHEDDABLE_LANES = (
    SignatureSetPriority.UNAGGREGATED,
    SignatureSetPriority.SYNC_COMMITTEE,
)


class _StubSet:
    """Opaque signature-set stand-in for stub runs (the pool only ever
    len()s and forwards sets; the stub verifier ignores their content)."""

    __slots__ = ()


class StubVerifier:
    """Deterministic stage-split verifier with a configurable capacity:
    pack blocks the calling thread for ``pack_ms``, the 'device' finishes
    ``dispatch_ms + per_set_us * n`` after enqueue, ``result()`` blocks
    until then — the TpuBlsVerifier timing shape without a TPU or a
    single XLA compile.  Defaults model a ~200 sets/s/chip device at
    batch 128 with pipelining headroom."""

    def __init__(self, pack_ms: float = 1.0, dispatch_ms: float = 4.0,
                 per_set_us: float = 50.0, n_devices: int = 1,
                 verdict: bool = True):
        self.pack_ms = pack_ms
        self.dispatch_ms = dispatch_ms
        self.per_set_us = per_set_us
        self.n_devices = n_devices
        self.verdict = verdict
        self.dispatches = 0
        self.sets_seen = 0

    def verify_signature_sets_async(self, sets, deadline: Optional[float] = None):
        time.sleep(self.pack_ms / 1e3)  # host pack (worker thread)
        self.dispatches += 1
        self.sets_seen += len(sets)
        ready_at = time.monotonic() + (
            self.dispatch_ms + self.per_set_us * len(sets) / 1e3
        ) / 1e3
        verdict = self.verdict

        class _Pending:
            device = "stub:0"

            def result(_self) -> bool:
                rem = ready_at - time.monotonic()
                if rem > 0:
                    time.sleep(rem)  # device sync (worker thread)
                return verdict

        return _Pending()

    def verify_signature_sets(self, sets):
        return self.verify_signature_sets_async(sets).result()

    def close(self) -> None:
        return None


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on empty input."""
    if not values:
        return None
    ordered = sorted(values)
    # nearest-rank: ceil(q/100 * n) as a 1-based rank (round() would
    # banker's-round x.5 to the EVEN neighbor and skew odd ranks up)
    k = max(0, min(len(ordered) - 1, math.ceil(q / 100.0 * len(ordered)) - 1))
    return ordered[k]


def _lat_stats(ms: List[float]) -> Dict[str, Any]:
    return {
        "n": len(ms),
        "p50_ms": round(percentile(ms, 50), 3) if ms else None,
        "p99_ms": round(percentile(ms, 99), 3) if ms else None,
        "max_ms": round(max(ms), 3) if ms else None,
    }


async def run_firehose(
    pool: BlsBatchPool,
    *,
    rate: float,
    duration_s: float,
    mix: Optional[Dict[str, float]] = None,
    deadline_ms: Optional[float] = None,
    sets_builder=None,
    respect_backpressure: bool = True,
    seed: int = 0,
    grace_s: float = 30.0,
) -> Dict[str, Any]:
    """Offer ``rate`` signature sets/sec of the duty ``mix`` to ``pool``
    for ``duration_s``, then drain and account for every job.

    ``deadline_ms`` (optional) stamps storm-lane jobs (unaggregated /
    sync-committee) with submit-time + deadline — the shed policy's
    input; block/aggregate jobs never carry one here.  ``sets_builder``
    maps a duty name to a list of real SignatureSets for real-verifier
    runs (stub runs use opaque placeholders).  ``respect_backpressure``
    makes the harness behave like gossip intake: while ``pool.overloaded``
    storm-lane jobs are shed at intake instead of submitted.
    """
    mix = dict(mix or DEFAULT_MIX)
    rng = random.Random(seed)
    duty_names = list(mix)
    weights = [mix[d] for d in duty_names]
    records: List[Tuple[str, str, float, int]] = []  # (duty, outcome, e2e_ms, n_sets)
    intake_shed: Dict[str, int] = {}
    offered_sets = 0
    submitted_sets = 0
    tasks: List[asyncio.Task] = []

    dropped_before = dict(pool.dropped_sets)

    async def one_job(duty: str, sets: List[Any], lane: SignatureSetPriority,
                      deadline: Optional[float]) -> None:
        t0 = time.monotonic()
        try:
            ok = await pool.verify_signature_sets(
                sets, priority=lane, deadline=deadline
            )
            outcome = "verified_ok" if ok else "verified_false"
        except VerificationDroppedError as e:
            outcome = f"dropped_{e.reason}"
        except Exception as e:  # noqa: BLE001 — the harness must account, not die
            outcome = f"error_{type(e).__name__}"
        records.append((duty, outcome, (time.monotonic() - t0) * 1e3, len(sets)))

    t_start = time.monotonic()
    budget = 0.0  # fractional sets earned by elapsed time
    last = t_start
    tick_s = max(0.001, min(0.01, 32.0 / max(rate, 1.0)))
    while True:
        now = time.monotonic()
        if now - t_start >= duration_s:
            break
        budget += (now - last) * rate
        last = now
        while budget >= 1.0:
            duty = rng.choices(duty_names, weights=weights, k=1)[0]
            lane, sets_per_job = DUTIES[duty]
            budget -= sets_per_job
            if (
                respect_backpressure
                and lane in SHEDDABLE_LANES
                and pool.overloaded
            ):
                # gossip-intake analog: storm topics slow under backpressure
                # (nominal size: the job's sets are never built)
                offered_sets += sets_per_job
                intake_shed[duty] = intake_shed.get(duty, 0) + sets_per_job
                continue
            sets = (
                sets_builder(duty) if sets_builder is not None
                else [_StubSet() for _ in range(sets_per_job)]
            )
            # offered counts what the builder ACTUALLY produced so the
            # accounting identity holds for non-nominal builders too
            offered_sets += len(sets)
            deadline = None
            if deadline_ms is not None and lane in SHEDDABLE_LANES:
                deadline = time.monotonic() + deadline_ms / 1e3
            submitted_sets += len(sets)
            tasks.append(asyncio.create_task(one_job(duty, sets, lane, deadline)))
        await asyncio.sleep(tick_s)

    # drain: every submitted job must resolve one way or another
    stranded = 0
    if tasks:
        done, pending = await asyncio.wait(tasks, timeout=grace_s)
        stranded = len(pending)
        for t in pending:
            t.cancel()
    wall_s = time.monotonic() - t_start

    # queue-wait distribution from the pool's own spans
    queue_wait_ms = [
        s.dur_ns / 1e6
        for s in tracing.TRACER.spans()
        if s.name == "bls.queue_wait"
    ]

    by_duty: Dict[str, List[float]] = {}
    outcomes: Dict[str, int] = {}
    verified_sets = 0
    errored_sets = 0
    for duty, outcome, e2e_ms, n_sets in records:
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
        # account the ACTUAL job size (a sets_builder may return a
        # non-nominal count), matching what submitted_sets summed
        if outcome.startswith("verified"):
            by_duty.setdefault(duty, []).append(e2e_ms)
            verified_sets += n_sets
        elif outcome.startswith("error_"):
            errored_sets += n_sets
    e2e_all = [ms for lat in by_duty.values() for ms in lat]

    dropped: Dict[str, int] = {}
    for key, n in pool.dropped_sets.items():
        delta = n - dropped_before.get(key, 0)
        if delta:
            dropped["/".join(key)] = delta
    dropped_sets_total = sum(dropped.values())
    intake_shed_total = sum(intake_shed.values())

    return {
        "offered_rate_sets_per_s": round(rate, 1),
        "duration_s": round(duration_s, 2),
        "wall_s": round(wall_s, 2),
        "offered_sets": offered_sets,
        "submitted_sets": submitted_sets,
        "verified_sets": verified_sets,
        "achieved_sets_per_s": round(verified_sets / wall_s, 1) if wall_s else None,
        # whole-mesh headline (ISSUE 7 satellite 2): what the NODE
        # sustained across every device, the per-chip twin of which is
        # bls_sets_per_sec_per_chip — named so the run ledger and the
        # roadmap item 1 success metric read one key
        "bls_sig_sets_per_s": round(verified_sets / wall_s, 1) if wall_s else None,
        "queue_wait": _lat_stats(queue_wait_ms),
        "e2e": _lat_stats(e2e_all),
        "e2e_by_duty": {d: _lat_stats(lat) for d, lat in sorted(by_duty.items())},
        "block_lane_p99_ms": _lat_stats(by_duty.get("block_proposal", []))["p99_ms"],
        "outcomes": dict(sorted(outcomes.items())),
        "dropped_sets": dropped,               # reason/lane -> sets, pool-accounted
        "dropped_sets_total": dropped_sets_total,
        "intake_shed_sets": intake_shed,       # backpressure at 'gossip' intake
        "intake_shed_total": intake_shed_total,
        "errored_sets": errored_sets,
        # the accounting identity the acceptance criteria demand: every
        # offered set is verified, typed-dropped, intake-shed, or errored
        "unaccounted_sets": offered_sets - submitted_sets - intake_shed_total
        + (submitted_sets - verified_sets - dropped_sets_total - errored_sets),
        "stranded_futures": stranded,
        "backpressure_now": pool.overloaded,
        "pending_sets_after": pool.pending_sets(),
        "spans_dropped": tracing.TRACER.dropped,
    }


def _parse_mix(arg: Optional[str]) -> Optional[Dict[str, float]]:
    if not arg:
        return None
    mix: Dict[str, float] = {}
    for part in arg.split(","):
        name, _, frac = part.partition("=")
        if name not in DUTIES:
            raise SystemExit(f"--mix: unknown duty {name!r} (know {sorted(DUTIES)})")
        mix[name] = float(frac)
    return mix


def _build_real_sets(kind: str, n_unique: int = 16):
    """Reusable real signature sets per duty for non-stub verifiers (the
    point cache makes reuse the realistic shape anyway)."""
    from lodestar_tpu.crypto.bls.api import interop_secret_key
    from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet

    pool_sets = []
    for i in range(n_unique):
        sk = interop_secret_key(i % 8)
        msg = bytes([i % 256, kind == "native"]) * 16
        pool_sets.append(
            SingleSignatureSet(
                pubkey=sk.to_public_key(), signing_root=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    counter = {"i": 0}

    def builder(duty: str):
        _, per_job = DUTIES[duty]
        out = []
        for _ in range(per_job):
            out.append(pool_sets[counter["i"] % len(pool_sets)])
            counter["i"] += 1
        return out

    return builder


def _make_verifier(kind: str):
    if kind == "stub":
        return StubVerifier(), None
    if kind == "python":
        from lodestar_tpu.crypto.bls.verifier import PyBlsVerifier

        return PyBlsVerifier(), _build_real_sets(kind)
    if kind == "native":
        from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier

        return FastBlsVerifier(), _build_real_sets(kind)
    if kind == "tpu":
        from lodestar_tpu.crypto.bls.tpu_verifier import (
            TpuBlsVerifier,
            configure_persistent_cache,
        )

        configure_persistent_cache()
        v = TpuBlsVerifier(buckets=(128,))
        v.warmup()
        return v, _build_real_sets(kind)
    raise SystemExit(f"unknown verifier {kind!r}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=2000.0,
                    help="offered signature sets per second")
    ap.add_argument("--seconds", type=float, default=10.0,
                    help="sustained-load window")
    ap.add_argument("--mix", default=None,
                    help="job mix, e.g. unaggregated=0.8,aggregate=0.12,"
                    "sync_committee=0.075,block_proposal=0.005")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="storm-lane job deadline (unaggregated/sync); "
                    "expired jobs are shed, not verified")
    ap.add_argument("--verifier", choices=("stub", "python", "native", "tpu"),
                    default="stub")
    ap.add_argument("--flush-threshold", type=int, default=128)
    ap.add_argument("--pipeline-depth", type=int, default=2)
    ap.add_argument("--max-queue-length", type=int, default=8192)
    ap.add_argument("--high-water", type=int, default=0,
                    help="backpressure high-water mark in pending sets "
                    "(0 = half the queue length)")
    ap.add_argument("--no-backpressure", action="store_true",
                    help="keep submitting storm lanes while the pool is "
                    "overloaded (measures eviction instead of intake shed)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    verifier, sets_builder = _make_verifier(args.verifier)
    tracing.TRACER.clear()
    tracing.enable(65536)
    pool = BlsBatchPool(
        verifier,
        max_buffer_wait=0.01,
        flush_threshold=args.flush_threshold,
        pipeline_depth=args.pipeline_depth,
        max_queue_length=args.max_queue_length,
        high_water=args.high_water or None,
    )

    async def run():
        try:
            return await run_firehose(
                pool,
                rate=args.rate,
                duration_s=args.seconds,
                mix=_parse_mix(args.mix),
                deadline_ms=args.deadline_ms,
                sets_builder=sets_builder,
                respect_backpressure=not args.no_backpressure,
                seed=args.seed,
            )
        finally:
            pool.close()

    report = asyncio.run(run())
    report["verifier"] = args.verifier
    print(json.dumps(report, indent=1))
    return 1 if (report["stranded_futures"] or report["unaccounted_sets"]) else 0


if __name__ == "__main__":
    raise SystemExit(main())
