#!/usr/bin/env python3
"""Tier-1 wall-time budget report: who is eating the 870 s cap.

Reads the run ledger ``tests/conftest.py`` appends to
``.jax_cache/tier1_timings.json`` (per-test setup+call+teardown wall
plus per-test compile-guard event counts, last 8 runs kept) and prints:

- the suite wall-time trend against the cap and the margin left;
- the top-10 movers vs the previous run (intersection of node ids — a
  test that got 13 s slower shows up here BEFORE the whole suite trips
  rc=124, which is how the <35 s-margin problem stays visible);
- the top-10 slowest tests of the latest run and which tests triggered
  expensive compile/cache-load events.

Usage:
    python tools/tier1_budget.py                 # report
    python tools/tier1_budget.py --json
    python tools/tier1_budget.py --fail-margin 35   # exit 1 when the
                                  # latest full run left < 35 s of cap
    python tools/tier1_budget.py --enforce       # fail-margin 60 PLUS the
                                  # compile-cost static audit: exit 1 on
                                  # any violation or thin margin

Partial runs (`pytest -k` subsets, below
run_ledger.TIER1_FULL_RUN_MIN_TESTS tests) live in their own ledger
ring (``partial_runs``): they are reported but never gate, and the
movers table always compares full-run against full-run — a `-k` subset
can no longer push the real baselines out of the last-8 window.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO_DEFAULT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_DEFAULT)

from lodestar_tpu.observatory.run_ledger import (  # noqa: E402
    TIER1_FULL_RUN_MIN_TESTS,
)

DEFAULT_CAP_S = 870.0


def load_ledger(repo: str) -> Dict[str, List[Dict[str, Any]]]:
    """Both rings, as ``{"full": [...], "partial": [...]}``.

    Schema 2 stores them separately; legacy schema-1 files (one mixed
    ``runs`` list) are split on read by the same absolute threshold the
    conftest writer uses, so old ledgers keep working."""
    path = os.path.join(repo, ".jax_cache", "tier1_timings.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {"full": [], "partial": []}
    runs = data.get("runs", [])
    partial = data.get("partial_runs", [])
    if data.get("schema", 1) < 2:
        full = [r for r in runs if r.get("n_tests", 0) >= TIER1_FULL_RUN_MIN_TESTS]
        partial = [r for r in runs if r.get("n_tests", 0) < TIER1_FULL_RUN_MIN_TESTS]
        runs = full
    return {"full": runs, "partial": partial}


def movers(prev: Dict[str, float], last: Dict[str, float],
           top: int = 10) -> List[Dict[str, Any]]:
    """Largest absolute per-test deltas over the shared node ids."""
    shared = set(prev) & set(last)
    deltas = [
        {
            "test": nodeid,
            "prev_s": prev[nodeid],
            "last_s": last[nodeid],
            "delta_s": round(last[nodeid] - prev[nodeid], 3),
        }
        for nodeid in shared
    ]
    deltas.sort(key=lambda d: -abs(d["delta_s"]))
    return deltas[:top]


def _run_summary(r: Dict[str, Any]) -> Dict[str, Any]:
    return {"wall_s": r.get("wall_s"), "n_tests": r.get("n_tests"),
            "exitstatus": r.get("exitstatus"),
            "compile_events": r.get("compile_events"),
            "compile_events_s": r.get("compile_events_s"),
            "aot": r.get("aot")}


def analyze(repo: str, cap_s: float = DEFAULT_CAP_S) -> Dict[str, Any]:
    rings = load_ledger(repo)
    runs, partial = rings["full"], rings["partial"]
    out: Dict[str, Any] = {
        "cap_s": cap_s,
        "runs": [_run_summary(r) for r in runs],
        "partial_runs": [_run_summary(r) for r in partial],
    }
    if not runs:
        return out
    last = runs[-1]
    out["last_wall_s"] = last.get("wall_s")
    out["margin_s"] = (
        round(cap_s - last["wall_s"], 1) if last.get("wall_s") is not None else None
    )
    # "full" is absolute (run_ledger.TIER1_FULL_RUN_MIN_TESTS), never
    # relative to the previous entry: two identical `pytest -k` subsets
    # must not validate each other into gating the cap, and the very
    # first ledger entry gets no benefit of the doubt either.  The
    # gating entry always comes off the FULL ring, so a stack of `-k`
    # subsets can never be the thing the margin is computed from.
    out["is_full_run"] = last.get("n_tests", 0) >= TIER1_FULL_RUN_MIN_TESTS
    prev_full = runs[-2] if len(runs) >= 2 else None
    if prev_full is not None:
        out["movers"] = movers(prev_full.get("tests", {}), last.get("tests", {}))
        if last.get("wall_s") and prev_full.get("wall_s"):
            out["wall_delta_s"] = round(last["wall_s"] - prev_full["wall_s"], 1)
    out["aot"] = last.get("aot")
    if partial:
        p = partial[-1]
        if p.get("utc") and last.get("utc") and p["utc"] > last["utc"]:
            # the most recent chronological run was a -k subset: margin
            # still reflects the older full run, flag the staleness
            out["newer_partial"] = True
    slowest = sorted(
        last.get("tests", {}).items(), key=lambda kv: -kv[1]
    )[:10]
    out["slowest"] = [{"test": t, "seconds": s} for t, s in slowest]
    out["compiling_tests"] = dict(
        sorted(last.get("test_compiles", {}).items(), key=lambda kv: -kv[1])[:10]
    )
    return out


def render(report: Dict[str, Any]) -> str:
    lines = [f"tier-1 budget (cap {report['cap_s']:.0f}s)"]
    if not report["runs"]:
        lines.append("  no recorded runs — run the suite once to seed the ledger")
        return "\n".join(lines)
    walls = " -> ".join(
        f"{r['wall_s']}s({r['n_tests']}t,rc{r['exitstatus']})"
        for r in report["runs"]
    )
    lines.append(f"  full runs: {walls}")
    if report.get("partial_runs"):
        pwalls = " -> ".join(
            f"{r['wall_s']}s({r['n_tests']}t,rc{r['exitstatus']})"
            for r in report["partial_runs"]
        )
        lines.append(f"  partial (-k) runs [never gate]: {pwalls}")
    if report.get("margin_s") is not None:
        ok = report["margin_s"] >= 60
        margin = f"margin {report['margin_s']}s"
        if sys.stdout.isatty():
            margin = f"\x1b[32m{margin}\x1b[0m" if ok else f"\x1b[31m{margin}\x1b[0m"
        elif not ok:
            margin += "  ⚠"
        lines.append(
            f"  latest full wall {report['last_wall_s']}s — {margin}"
            + ("  [a newer -k subset ran since]" if report.get("newer_partial")
               else "")
        )
    if report.get("wall_delta_s") is not None:
        lines.append(f"  wall delta vs previous full run: {report['wall_delta_s']:+}s")
    if report.get("aot"):
        a = report["aot"]
        lines.append(
            f"  AOT executable store (latest run): hits={a.get('hits')} "
            f"misses={a.get('misses')} saves={a.get('saves')} "
            f"corrupt={a.get('corrupt')} skew={a.get('skew')} "
            f"(docs/aot.md — hits skip trace+lower+backend-load entirely)"
        )
    if report.get("movers"):
        lines.append("  top movers vs previous run:")
        for m in report["movers"]:
            lines.append(
                f"    {m['delta_s']:+8.2f}s  {m['test']}  "
                f"({m['prev_s']} -> {m['last_s']})"
            )
    if report.get("slowest"):
        lines.append("  slowest tests (latest run):")
        for s in report["slowest"]:
            lines.append(f"    {s['seconds']:8.2f}s  {s['test']}")
    if report.get("compiling_tests"):
        lines.append("  compile-guard events by test (latest run):")
        for t, n in report["compiling_tests"].items():
            lines.append(f"    {n:3d}  {t}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=_REPO_DEFAULT)
    ap.add_argument("--cap", type=float, default=DEFAULT_CAP_S)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--fail-margin", type=float, default=None, metavar="S",
                    help="exit 1 when the latest FULL run left less than "
                    "this many seconds of cap margin")
    ap.add_argument("--enforce", action="store_true",
                    help="CI gate: --fail-margin 60 combined with the "
                    "compile-cost static audit — exit nonzero on any "
                    "compile-cost violation OR a thin margin")
    args = ap.parse_args(argv)
    if args.enforce and args.fail_margin is None:
        args.fail_margin = 60.0
    report = analyze(args.repo, cap_s=args.cap)
    rc = 0
    if args.enforce:
        from lodestar_tpu.analysis.compile_cost import audit_compile_cost
        from lodestar_tpu.analysis.report import format_report, to_dicts

        violations = audit_compile_cost(repo=args.repo)
        report["compile_cost_violations"] = to_dicts(violations)
        if violations:
            print(format_report(violations), file=sys.stderr)
            rc = 1
    print(json.dumps(report, indent=1) if args.json else render(report))
    if (
        args.fail_margin is not None
        and report.get("margin_s") is not None
        and report.get("is_full_run")
        and report["margin_s"] < args.fail_margin
    ):
        print(
            f"tier-1 margin {report['margin_s']}s < {args.fail_margin}s",
            file=sys.stderr,
        )
        rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
