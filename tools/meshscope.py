#!/usr/bin/env python3
"""Mesh observatory report: per-batch latency attribution + scaling-loss
breakdown from a trace dump (docs/observability.md §Mesh observatory).

Feed it any Chrome trace the stack produces — a ``--trace-dump`` file, a
``/eth/v1/lodestar/traces?format=chrome`` download, or (best) the merged
host+device dump from ``POST /eth/v1/lodestar/profile?format=chrome`` /
``--jax-profile``'s ``merged_trace.json`` — and it prints, per merged
batch, the six-way split queue / pack / device-compute /
collective-combine / final-exp / pipeline-bubble, the compute/pack
overlap ratio, and (when mesh batches are present) the live
scaling-loss breakdown.  With device events in the dump the
device-compute vs collective split is measured; span-only dumps fall
back to the host-side dispatch wall.

Usage:
    python tools/meshscope.py MERGED_TRACE.json [--json]
                              [--tolerance FRAC] [--fail-on-residual]

Exit codes: 0 ok, 1 unreadable/attributable input, 2 (with
--fail-on-residual) a mesh breakdown whose components do not sum to the
gap within the tolerance.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO_DEFAULT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_DEFAULT)

from lodestar_tpu.observatory import attribution  # noqa: E402


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:8.3f}"


def render(report: dict, breakdown: Optional[dict]) -> str:
    lines: List[str] = []
    batches = report["batches"]
    lines.append(
        f"{len(batches)} merged batch(es); "
        f"overlap_ratio={report['overlap_ratio']}"
    )
    lines.append("")
    header = (
        f"{'cid':>6} {'dev':>8} {'mesh':>4} | {'queue':>8} {'pack':>8} "
        f"{'device':>8} {'combine':>8} {'finexp':>8} {'bubble':>8} "
        f"| {'e2e ms':>8} {'expl':>5}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for b in batches:
        s = b["stages"]
        lines.append(
            f"{str(b['cid']):>6} {str(b['device'] or '-'):>8} "
            f"{str(b['mesh_devices'] or '-'):>4} | "
            f"{_fmt_ms(s['queue'])} {_fmt_ms(s['pack'])} "
            f"{_fmt_ms(s['device_compute'])} "
            f"{_fmt_ms(s['collective_combine'])} "
            f"{_fmt_ms(s['final_exp'])} {_fmt_ms(s['pipeline_bubble'])} | "
            f"{_fmt_ms(b['e2e_s'])} {b['explained_ratio']:>5}"
        )
    lines.append("")
    if breakdown is None:
        lines.append("no mesh (sharded) batches: scaling-loss breakdown n/a")
    else:
        c = breakdown["components"]
        lines.append(
            f"mesh scaling loss (live estimate): "
            f"efficiency={breakdown['efficiency']} "
            f"loss={breakdown['loss']}"
        )
        lines.append(
            f"  communication={c['communication']} "
            f"shard_imbalance={c['shard_imbalance']} "
            f"serial_host={c['serial_host']}"
        )
        lines.append(
            f"  explained={breakdown['explained']} "
            f"residual={breakdown['residual']} "
            f"within_tolerance={breakdown['within_tolerance']}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace JSON (merged or span-only)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="scaling-loss reconciliation tolerance (fraction "
                    "of the gap, default 0.05)")
    ap.add_argument("--fail-on-residual", action="store_true",
                    help="exit 2 when the breakdown components do not sum "
                    "to the gap within --tolerance")
    args = ap.parse_args(argv)
    try:
        with open(args.trace) as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        print(f"{args.trace}: unreadable trace: {e}", file=sys.stderr)
        return 1
    events = trace.get("traceEvents", trace) if isinstance(trace, dict) else trace
    if not isinstance(events, list):
        print(f"{args.trace}: no traceEvents list", file=sys.stderr)
        return 1
    report = attribution.attribute_spans(events)
    if not report["batches"]:
        print(f"{args.trace}: no attributable merged batches "
              f"(needs cid-correlated bls.* spans)", file=sys.stderr)
        return 1
    breakdown = attribution.mesh_scaling_loss(
        report["batches"], tolerance=args.tolerance
    )
    if args.json:
        print(json.dumps({"attribution": report, "scaling_loss": breakdown},
                         indent=1))
    else:
        print(render(report, breakdown))
    if (args.fail_on_residual and breakdown is not None
            and not breakdown["within_tolerance"]):
        print("scaling-loss components do not reconcile with the gap",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
