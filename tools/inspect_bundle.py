#!/usr/bin/env python3
"""Validate and summarize a diagnostic bundle (lodestar_tpu/forensics).

Usage:
    python tools/inspect_bundle.py BUNDLE_DIR [--json]

Validation (exit 1 with one error per line on failure):

- ``manifest.json`` present, parses, ``schema`` is a supported version,
  and the required keys (reason/created_unix/pid/files/journal/trace/
  inflight) are present;
- every file the manifest lists actually exists in the bundle — the
  manifest is written LAST, so a listed-but-missing file means a
  corrupted bundle, not an interrupted dump;
- the manifest notes its drop counts (``journal.dropped`` /
  ``trace.dropped``) so a reader knows how much history is missing;
- ``journal.jsonl`` is one JSON object per line, each carrying the
  REQUIRED_EVENT_KEYS of the journal schema, in ``seq`` order;
- ``trace.json`` passes the Chrome trace-event schema of
  tools/check_trace.py (including its own drop-count note);
- ``inflight.json`` parses and its ``inflight`` table is a list.

Summary (the triage view — what a responder needs FIRST after a death):

- reason, wall time, pid, and any per-section dump errors;
- the last JAX compile/cache event (was a compile in flight?);
- stalled batches: cid, device, bucket, age at flag time;
- per-device in-flight counts at dump time;
- the last ERROR/WARNING journal events (the stderr that got lost);
- chaos triage (docs/chaos.md): the armed fault plan's seed, the last
  injected fault (seam + context), requeued-batch count, per-executor
  health states, and the quarantine/re-admission timeline;
- AOT store triage (docs/aot.md): the store path, the last
  ``aot.corrupt``/``aot.skew`` events, and the per-entry load outcome
  timeline (loads/misses/saves) — the first questions after a restart
  that came up slow or degraded.

``--json`` prints the summary as one JSON object instead of text
(bench tooling and tests consume this form).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from lodestar_tpu.forensics.bundle import BUNDLE_SCHEMA, MANIFEST_NAME  # noqa: E402
from lodestar_tpu.forensics.journal import REQUIRED_EVENT_KEYS  # noqa: E402


def _load_check_trace():
    spec = importlib.util.spec_from_file_location(
        "check_trace", os.path.join(_REPO, "tools", "check_trace.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


MANIFEST_REQUIRED = (
    "schema", "reason", "created_unix", "pid", "files",
    "journal", "trace", "inflight",
)


def validate(bundle_dir: str) -> List[str]:
    """Schema errors for one bundle directory (empty list = valid)."""
    errors: List[str] = []
    manifest_path = os.path.join(bundle_dir, MANIFEST_NAME)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{MANIFEST_NAME}: unreadable ({e}) — bundle incomplete or corrupt"]
    for key in MANIFEST_REQUIRED:
        if key not in manifest:
            errors.append(f"{MANIFEST_NAME}: missing required key {key!r}")
    schema = manifest.get("schema")
    if schema != BUNDLE_SCHEMA:
        errors.append(
            f"{MANIFEST_NAME}: schema {schema!r} != supported {BUNDLE_SCHEMA}"
        )
    # drop-count notes: a dump that cannot say how much history it is
    # missing is not a flight recorder, it is a guess
    for section in ("journal", "trace"):
        meta = manifest.get(section)
        if isinstance(meta, dict) and not isinstance(meta.get("dropped"), int):
            errors.append(f"{MANIFEST_NAME}: {section}.dropped count missing")
    for fname in manifest.get("files", []):
        if not os.path.exists(os.path.join(bundle_dir, fname)):
            errors.append(f"{fname}: listed in manifest but absent")

    jpath = os.path.join(bundle_dir, "journal.jsonl")
    if os.path.exists(jpath):
        prev_seq = None
        for lineno, line in enumerate(open(jpath), 1):
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                errors.append(f"journal.jsonl:{lineno}: not valid JSON")
                continue
            for key in REQUIRED_EVENT_KEYS:
                if key not in ev:
                    errors.append(f"journal.jsonl:{lineno}: missing {key!r}")
            seq = ev.get("seq")
            if isinstance(seq, int) and prev_seq is not None and seq <= prev_seq:
                errors.append(
                    f"journal.jsonl:{lineno}: seq {seq} not increasing "
                    f"(prev {prev_seq})"
                )
            if isinstance(seq, int):
                prev_seq = seq

    tpath = os.path.join(bundle_dir, "trace.json")
    if os.path.exists(tpath):
        check_trace = _load_check_trace()
        try:
            with open(tpath) as f:
                trace = json.load(f)
        except ValueError as e:
            errors.append(f"trace.json: not valid JSON ({e})")
        else:
            errors.extend(f"trace.json: {e}" for e in check_trace.validate(trace))
            if isinstance(trace, dict) and not isinstance(
                (trace.get("otherData") or {}).get("dropped_spans"), int
            ):
                errors.append("trace.json: otherData.dropped_spans note missing")

    ipath = os.path.join(bundle_dir, "inflight.json")
    if os.path.exists(ipath):
        try:
            with open(ipath) as f:
                inflight = json.load(f)
        except ValueError as e:
            errors.append(f"inflight.json: not valid JSON ({e})")
        else:
            if not isinstance(inflight.get("inflight"), list):
                errors.append("inflight.json: 'inflight' table missing or not a list")
    return errors


def _journal_events(bundle_dir: str) -> List[Dict[str, Any]]:
    path = os.path.join(bundle_dir, "journal.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    for line in open(path):
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


def summarize(bundle_dir: str) -> Dict[str, Any]:
    """The triage summary: what was this process doing when it died."""
    with open(os.path.join(bundle_dir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    events = _journal_events(bundle_dir)
    compiles = [e for e in events if e.get("kind") == "jax.compile"]
    errors_log = [e for e in events if e.get("level") in ("ERROR", "CRITICAL")]
    warnings_log = [e for e in events if e.get("level") == "WARNING"]
    inflight = manifest.get("inflight") or []
    per_device: Dict[str, int] = {}
    for e in inflight:
        dev = str(e.get("device"))
        per_device[dev] = per_device.get(dev, 0) + 1
    inflight_file: Optional[Dict[str, Any]] = None
    ipath = os.path.join(bundle_dir, "inflight.json")
    if os.path.exists(ipath):
        try:
            with open(ipath) as f:
                inflight_file = json.load(f)
        except ValueError:
            pass
    # chaos triage (docs/chaos.md): what was INDUCED (manifest.chaos from
    # the armed fault plan), what the self-healing pool did about it
    # (bls.requeue / bls.health journal events), and where every executor's
    # health state machine stands (inflight.json verifier.health)
    chaos_manifest = manifest.get("chaos") or {}
    injected = chaos_manifest.get("injected") or []
    requeues = [e for e in events if e.get("kind") == "bls.requeue"]
    health_events = [e for e in events if e.get("kind") == "bls.health"]
    health_timeline = [
        {k: e.get(k) for k in ("wall", "device", "state", "failures",
                               "backoff_s", "readmitted")}
        for e in health_events
    ]
    verifier_stats = (inflight_file or {}).get("verifier") or {}
    # AOT store triage (docs/aot.md): what the durable executable tier
    # did — per-entry load outcomes plus the last corruption/skew events
    aot_events = [
        e for e in events if str(e.get("kind", "")).startswith("aot.")
    ]
    aot_summary: Optional[Dict[str, Any]] = None
    if aot_events:
        corrupts = [e for e in aot_events if e.get("kind") == "aot.corrupt"]
        skews = [e for e in aot_events if e.get("kind") == "aot.skew"]
        store_paths = [e.get("store") for e in aot_events if e.get("store")]
        aot_summary = {
            "store": store_paths[-1] if store_paths else None,
            "loads": sum(1 for e in aot_events if e.get("kind") == "aot.load"),
            "misses": sum(1 for e in aot_events if e.get("kind") == "aot.miss"),
            "saves": sum(1 for e in aot_events if e.get("kind") == "aot.save"),
            "corrupt": len(corrupts),
            "skew": len(skews),
            "last_corrupt": corrupts[-1] if corrupts else None,
            "last_skew": skews[-1] if skews else None,
            "outcomes": [
                {k: e.get(k) for k in ("wall", "kind", "entry", "bucket",
                                       "device", "seconds", "what", "reason")}
                for e in aot_events[-10:]
            ],
        }
    chaos_summary: Optional[Dict[str, Any]] = None
    if injected or requeues or health_events or chaos_manifest:
        chaos_summary = {
            "armed": chaos_manifest.get("armed"),
            "seed": chaos_manifest.get("seed"),
            "last_fault": injected[-1] if injected else None,
            "injected_total": len(injected),
            "requeued_batches": len(requeues),
            "executor_health": verifier_stats.get("health"),
            "health_timeline": health_timeline,
        }
    return {
        "bundle": bundle_dir,
        "reason": manifest.get("reason"),
        "created_unix": manifest.get("created_unix"),
        "pid": manifest.get("pid"),
        "schema": manifest.get("schema"),
        "chaos": chaos_summary,
        "aot": aot_summary,
        "dump_errors": manifest.get("errors"),
        "journal_events": manifest.get("journal", {}).get("events"),
        "journal_dropped": manifest.get("journal", {}).get("dropped"),
        "trace_spans": manifest.get("trace", {}).get("spans"),
        "trace_dropped": manifest.get("trace", {}).get("dropped"),
        "last_compile": compiles[-1] if compiles else None,
        # overload bundles (shed-rate trigger, chain/bls_pool): per-lane
        # shed counts and queue depth at trigger — the first thing a
        # responder needs for a "node under storm" death
        "overload": manifest.get("overload"),
        "stalled": [
            {
                k: e.get(k)
                for k in ("cid", "device", "bucket", "sets", "age_s", "deadline_s")
            }
            for e in manifest.get("stalled") or []
        ],
        "inflight_per_device": per_device,
        "inflight_total": len(inflight),
        "verifier": (inflight_file or {}).get("verifier"),
        "pool": (inflight_file or {}).get("pool"),
        "last_errors": errors_log[-5:],
        "last_warnings": warnings_log[-5:],
    }


def _print_text(s: Dict[str, Any]) -> None:
    print(f"bundle   {s['bundle']}")
    print(f"reason   {s['reason']}  (pid {s['pid']}, schema {s['schema']})")
    print(f"journal  {s['journal_events']} events ({s['journal_dropped']} dropped)")
    print(f"trace    {s['trace_spans']} spans ({s['trace_dropped']} dropped)")
    if s["dump_errors"]:
        print(f"dump errors: {s['dump_errors']}")
    lc = s["last_compile"]
    if lc:
        print(f"last compile  {lc.get('event')}  {lc.get('seconds')}s "
              f"(wall {lc.get('wall')})")
    else:
        print("last compile  none recorded")
    ov = s.get("overload")
    if ov:
        print(f"OVERLOAD: {ov.get('shed_window_sets')} sets shed in the last "
              f"{ov.get('window_s')}s; queue {ov.get('queue_depth_jobs')} jobs "
              f"/ {ov.get('pending_sets')} sets; "
              f"backpressure={'on' if ov.get('backpressure') else 'off'}")
        if ov.get("dropped_by_lane"):
            for lane, n in sorted(ov["dropped_by_lane"].items()):
                print(f"  shed lane {lane:15s} {n} sets")
        if ov.get("dropped_by_reason"):
            for reason, n in sorted(ov["dropped_by_reason"].items()):
                print(f"  shed reason {reason:13s} {n} sets")
    aot = s.get("aot")
    if aot:
        print(f"AOT store  {aot.get('store')}  loads={aot.get('loads')} "
              f"misses={aot.get('misses')} saves={aot.get('saves')} "
              f"corrupt={aot.get('corrupt')} skew={aot.get('skew')}")
        lc = aot.get("last_corrupt")
        if lc:
            print(f"  last corrupt  {lc.get('what')} entry={lc.get('entry')} "
                  f"b{lc.get('bucket')} {lc.get('device')} (wall {lc.get('wall')})")
        ls = aot.get("last_skew")
        if ls:
            print(f"  last skew     {ls.get('reason')} entry={ls.get('entry')} "
                  f"b{ls.get('bucket')} {ls.get('device')} (wall {ls.get('wall')})")
        for e in aot.get("outcomes") or []:
            print(f"  {e.get('wall')}  {e.get('kind'):12s} "
                  f"{e.get('entry')} b{e.get('bucket')} {e.get('device')}")
    ch = s.get("chaos")
    if ch:
        lf = ch.get("last_fault") or {}
        print(f"CHAOS: plan {'armed' if ch.get('armed') else 'disarmed'} "
              f"(seed {ch.get('seed')}), {ch.get('injected_total')} fault(s) "
              f"injected, {ch.get('requeued_batches')} batch(es) requeued")
        if lf:
            print(f"  last fault  seam={lf.get('seam')} seed={lf.get('seed')} "
                  f"ctx={lf.get('ctx')}")
        for dev, h in sorted((ch.get("executor_health") or {}).items()):
            extra_h = ""
            if h.get("readmission_in_s") is not None:
                extra_h = f" readmission in {h['readmission_in_s']}s"
            print(f"  health {dev:12s} {h.get('state'):11s} "
                  f"failures={h.get('failures')} "
                  f"quarantines={h.get('quarantines')}{extra_h}")
        for e in ch.get("health_timeline") or []:
            tag = " (re-admitted)" if e.get("readmitted") else ""
            print(f"  {e.get('wall')}  {e.get('device')} -> {e.get('state')}"
                  f"{tag} failures={e.get('failures')}")
    if s["stalled"]:
        print("STALLED batches:")
        for e in s["stalled"]:
            dl = e.get("deadline_s")
            worth = "" if dl is None else (
                f" deadline_headroom={dl}s" + (" (EXPIRED)" if dl < 0 else "")
            )
            print(f"  cid={e['cid']} device={e['device']} bucket={e['bucket']} "
                  f"sets={e['sets']} age={e['age_s']}s{worth}")
    print(f"in flight at dump: {s['inflight_total']} "
          f"(per device: {s['inflight_per_device'] or '{}'})")
    for e in s["last_errors"]:
        print(f"  ERROR  {e.get('kind')}: {e.get('msg') or e.get('exc') or e.get('error') or e}")
    for e in s["last_warnings"]:
        print(f"  WARN   {e.get('kind')}: {e.get('msg') or e.get('exc') or e.get('error') or e}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle_dir")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.bundle_dir):
        print(f"{args.bundle_dir}: not a directory", file=sys.stderr)
        return 1
    errors = validate(args.bundle_dir)
    for err in errors:
        print(f"{args.bundle_dir}: {err}", file=sys.stderr)
    if errors:
        return 1
    summary = summarize(args.bundle_dir)
    if args.json:
        print(json.dumps(summary, default=str))
    else:
        _print_text(summary)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
