#!/usr/bin/env python3
"""Validate a trace dump against the Chrome trace-event schema.

Usage:
    python tools/check_trace.py TRACE.json [--require-pipeline [N]]
                                [--require-device [TOL_US]]

Checks (the subset of the Trace Event Format spec that chrome://tracing
and Perfetto actually require to load a file):

- top level is an object with a ``traceEvents`` list (or a bare list);
- every event is an object with a string ``name`` and a string ``ph``;
- ``X``/``B``/``E``/``i``/``I`` events carry a numeric ``ts``;
- complete events (``ph == "X"``) carry a numeric non-negative ``dur``;
- ``pid``/``tid``, when present, are integers;
- ``args``, when present, is an object;
- object-form dumps note their drop count
  (``otherData.dropped_spans``) — a dump that cannot say how much
  history the ring evicted under it is silently lying about coverage.

``--require-pipeline [N]`` additionally asserts the dump contains the
full BLS span taxonomy — ``bls.queue_wait`` / ``bls.pack`` /
``bls.dispatch`` / ``bls.final_exp`` — with non-zero durations, batch-
correlated (same ``args.cid``) for at least N distinct merged batches
(default 2).  When the dump comes from a multi-device executor pool
(any ``bls.dispatch`` span carries ``args.devices_total > 1``) it also
asserts the dispatches landed on >= 2 distinct ``args.device`` ids — a
pool that funnels every batch to one chip is a scheduler bug, not a
pipeline.  Mesh dispatch (the sharded tier, docs/multichip.md): a
dispatch span carrying ``args.sharded`` must also carry
``args.mesh_devices >= 2`` and a ``devices_total > 1`` — a "sharded"
batch that reports one device never left a single chip; conversely one
sharded span with ``mesh_devices >= 2`` satisfies the distinct-device
requirement by itself (the mesh program spans every chip).  ``bls.shed`` spans (overload policy) exclude their cid from
the pipeline requirement; ``bls.requeue`` spans (self-healing pool,
docs/chaos.md) do NOT — a requeued cid must still complete its pipeline
via the replay, and must show >= 2 ``bls.dispatch`` attempts.  This is
the acceptance gate for a ``--trace-dump`` dev-chain run;
tests/test_tracing.py drives it in-process.

``--require-device [TOL_US]`` validates a MERGED host+device dump (the
mesh observatory's xprof output, docs/observability.md §Mesh
observatory): device events must live in renumbered processes at
``pid >= 1000`` (one ``process_name`` metadata event each — the
profiler pid/tid convention after the merge), host spans must remain at
pid 0, the dump must carry its clock mapping
(``otherData.device_clock``: numeric ``offset_us``/``skew_us``/
``tolerance_us``), the remapped device events must share the host
clock (their window overlaps the host span window), and a recorded
skew beyond tolerance (TOL_US overrides the dump's own) fails — a
merge whose clocks drifted is two timelines glued together, not one.

Exit 0 on success; exit 1 with one error per line on failure.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List

PIPELINE_SPANS = ("bls.queue_wait", "bls.pack", "bls.dispatch", "bls.final_exp")
#: spans that legitimately END a batch early: a cid whose jobs were shed
#: by the overload policy (chain/bls_pool deadline shedding) never reaches
#: pack/dispatch — --require-pipeline must not count it as a broken
#: pipeline, and its presence is reported, not errored
SHED_SPAN = "bls.shed"
#: a failed in-flight batch re-dispatched onto a surviving executor
#: (self-healing pool, docs/chaos.md).  A requeued cid must STILL satisfy
#: --require-pipeline — the replay emits fresh dispatch/final_exp spans —
#: and additionally must show >= 2 dispatch attempts (a requeue span with
#: no re-dispatch means the recovery path lost the batch)
REQUEUE_SPAN = "bls.requeue"
_TS_PHASES = {"X", "B", "E", "i", "I"}
#: merged-trace device processes start here (the
#: lodestar_tpu/observatory/xprof.py DEVICE_PID_BASE convention; the
#: value is duplicated so this tool stays runnable with no package
#: on the path)
DEVICE_PID_BASE = 1000


def validate(trace: Any) -> List[str]:
    """Schema errors for a parsed trace object (empty list = valid)."""
    errors: List[str] = []
    if isinstance(trace, dict):
        events = trace.get("traceEvents")
        if not isinstance(events, list):
            return ["top-level object has no traceEvents list"]
        if not isinstance(
            (trace.get("otherData") or {}).get("dropped_spans"), int
        ):
            errors.append(
                "otherData.dropped_spans missing: the dump must note how "
                "many spans the ring evicted"
            )
    elif isinstance(trace, list):
        events = trace
    else:
        return [f"trace must be an object or array, got {type(trace).__name__}"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: event is not an object")
            continue
        if not isinstance(ev.get("name"), str):
            errors.append(f"{where}: missing string 'name'")
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            errors.append(f"{where}: missing string 'ph'")
            continue
        if ph in _TS_PHASES and not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"{where} ({ev.get('name')}): ph={ph} requires numeric 'ts'")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(
                    f"{where} ({ev.get('name')}): complete event requires "
                    f"non-negative numeric 'dur'"
                )
        for key in ("pid", "tid"):
            if key in ev and not isinstance(ev[key], int):
                errors.append(f"{where}: '{key}' must be an integer")
        if "args" in ev and not isinstance(ev["args"], dict):
            errors.append(f"{where}: 'args' must be an object")
    return errors


def validate_pipeline(trace: Any, min_batches: int = 2) -> List[str]:
    """BLS-pipeline errors: every PIPELINE_SPANS stage present with dur>0
    under the same cid, for >= min_batches distinct cids; and, for a
    multi-device dump (dispatch spans carrying ``devices_total > 1``),
    dispatches spread over >= 2 distinct device ids."""
    events = trace.get("traceEvents", trace) if isinstance(trace, dict) else trace
    by_cid: Dict[Any, Dict[str, float]] = {}
    shed_cids = set()
    requeued_cids = set()
    dispatches_by_cid: Dict[Any, int] = {}
    devices_seen = set()
    devices_total = 1
    mesh_covered = False  # a sharded span with mesh_devices >= 2 seen
    mesh_errors: List[str] = []
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = ev.get("name")
        if name == SHED_SPAN:
            cid = (ev.get("args") or {}).get("cid", ev.get("id"))
            if cid is not None:
                shed_cids.add(cid)
            continue
        if name == REQUEUE_SPAN:
            cid = (ev.get("args") or {}).get("cid", ev.get("id"))
            if cid is not None:
                requeued_cids.add(cid)
            continue
        if name not in PIPELINE_SPANS:
            continue
        args = ev.get("args") or {}
        if name == "bls.dispatch":
            devices_total = max(devices_total, int(args.get("devices_total", 1)))
            if args.get("sharded"):
                # mesh dispatch contract: the span must say how many
                # chips the batch actually spanned, and a sharded batch
                # on a 1-device "mesh" is the scheduler lying
                mesh_n = args.get("mesh_devices")
                if not isinstance(mesh_n, int) or mesh_n < 2:
                    mesh_errors.append(
                        f"pipeline: sharded bls.dispatch span (cid "
                        f"{args.get('cid')}) must carry integer "
                        f"args.mesh_devices >= 2, got {mesh_n!r}"
                    )
                elif int(args.get("devices_total", 1)) <= 1:
                    mesh_errors.append(
                        f"pipeline: sharded bls.dispatch span (cid "
                        f"{args.get('cid')}) reports devices_total == 1 — "
                        f"a mesh-spanning batch on a single-device pool "
                        f"is not sharded"
                    )
                else:
                    mesh_covered = True
            elif args.get("device") is not None:
                devices_seen.add(args["device"])
        cid = args.get("cid", ev.get("id"))
        if cid is None:
            continue
        if name == "bls.dispatch":
            dispatches_by_cid[cid] = dispatches_by_cid.get(cid, 0) + 1
        stages = by_cid.setdefault(cid, {})
        stages[name] = max(stages.get(name, 0.0), float(ev.get("dur", 0)))
    complete = [
        cid
        for cid, stages in by_cid.items()
        if all(stages.get(s, 0.0) > 0.0 for s in PIPELINE_SPANS)
    ]
    errors: List[str] = []
    if len(complete) < min_batches:
        # a cid whose jobs were entirely shed (bls.shed) is an overload
        # decision, not a broken pipeline — exclude it from the partials
        partial = {
            cid: sorted(st)
            for cid, st in by_cid.items()
            if cid not in shed_cids
        }
        errors.append(
            f"pipeline: need >= {min_batches} batches with correlated non-zero "
            f"{'/'.join(PIPELINE_SPANS)} spans, found {len(complete)} "
            f"({len(shed_cids)} shed batches excluded; "
            f"partial batches: {partial})"
        )
    errors.extend(mesh_errors)
    # one valid mesh-spanning dispatch covers every chip by construction
    if devices_total > 1 and len(devices_seen) < 2 and not mesh_covered:
        errors.append(
            f"pipeline: multi-device dump (devices_total={devices_total}) but "
            f"dispatches landed on {sorted(devices_seen)} — expected >= 2 "
            f"distinct device ids (or a sharded mesh dispatch)"
        )
    # a requeued batch (bls.requeue) must show its replay: >= 2 dispatch
    # attempts under the same cid, else the recovery path lost the batch
    for cid in sorted(requeued_cids, key=str):
        if dispatches_by_cid.get(cid, 0) < 2:
            errors.append(
                f"pipeline: cid {cid} carries a {REQUEUE_SPAN} span but only "
                f"{dispatches_by_cid.get(cid, 0)} bls.dispatch attempt(s) — "
                f"a requeue must re-dispatch on a surviving executor"
            )
    return errors


def validate_device_merge(trace: Any, tolerance_us: float = None) -> List[str]:
    """Merged host+device dump errors (empty list = valid merge).

    Requires: object form with ``otherData.device_clock`` (numeric
    offset/skew/tolerance), >= 1 complete device event at
    ``pid >= DEVICE_PID_BASE`` with a ``process_name`` metadata event
    per device process, host spans still at pid 0, the remapped device
    window overlapping the host window (shared clock), and
    ``|skew_us| <= tolerance`` (``tolerance_us`` overrides the dump's)."""
    errors: List[str] = []
    if not isinstance(trace, dict):
        return ["device-merge: merged dumps must use the object form "
                "(otherData carries the clock mapping)"]
    clock = (trace.get("otherData") or {}).get("device_clock")
    if not isinstance(clock, dict):
        return ["device-merge: otherData.device_clock missing — a merged "
                "dump must record how the profiler timebase was mapped"]
    for key in ("offset_us", "skew_us", "tolerance_us"):
        if not isinstance(clock.get(key), (int, float)):
            errors.append(
                f"device-merge: device_clock.{key} must be numeric, "
                f"got {clock.get(key)!r}"
            )
    if errors:
        return errors
    tol = float(tolerance_us) if tolerance_us is not None else float(
        clock["tolerance_us"]
    )
    if abs(float(clock["skew_us"])) > tol:
        errors.append(
            f"device-merge: clock skew {clock['skew_us']:.1f}us exceeds "
            f"tolerance {tol:.1f}us — the device timeline cannot be "
            f"trusted against the host spans"
        )
    events = trace.get("traceEvents") or []
    named_pids = set()
    device_windows: List[tuple] = []
    host_windows: List[tuple] = []
    device_pids = set()
    for ev in events:
        if not isinstance(ev, dict):
            continue
        pid = ev.get("pid")
        if not isinstance(pid, int):
            continue
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            named_pids.add(pid)
            continue
        if ev.get("ph") != "X":
            continue
        ts, dur = ev.get("ts"), ev.get("dur", 0)
        if not isinstance(ts, (int, float)):
            continue
        window = (float(ts), float(ts) + float(dur or 0))
        if pid >= DEVICE_PID_BASE:
            device_pids.add(pid)
            device_windows.append(window)
        elif pid == 0:
            host_windows.append(window)
    if not device_windows:
        errors.append(
            f"device-merge: no complete device events at "
            f"pid >= {DEVICE_PID_BASE} — the merge carried no profile"
        )
    if not host_windows:
        errors.append(
            "device-merge: no host spans at pid 0 — the merge lost the "
            "span-tracer timeline"
        )
    for pid in sorted(device_pids):
        if pid not in named_pids:
            errors.append(
                f"device-merge: device process {pid} has no process_name "
                f"metadata event (the profiler pid convention)"
            )
    if device_windows and host_windows:
        d0 = min(a for a, _ in device_windows)
        d1 = max(b for _, b in device_windows)
        h0 = min(a for a, _ in host_windows)
        h1 = max(b for _, b in host_windows)
        if d1 < h0 - tol or d0 > h1 + tol:
            errors.append(
                f"device-merge: remapped device window "
                f"[{d0:.1f}, {d1:.1f}]us does not overlap the host window "
                f"[{h0:.1f}, {h1:.1f}]us (±{tol:.1f}us) — the clocks were "
                f"not actually shared"
            )
    return errors


def _optional_float(argv: List[str], flag: str):
    """(present, value|None) for a flag with an optional numeric arg."""
    if flag not in argv:
        return False, None
    idx = argv.index(flag)
    if idx + 1 < len(argv):
        try:
            return True, float(argv[idx + 1])
        except ValueError:
            pass
    return True, None


def main(argv: List[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 1
    path = argv[0]
    min_batches = 2
    require_pipeline = "--require-pipeline" in argv
    if require_pipeline:
        idx = argv.index("--require-pipeline")
        if idx + 1 < len(argv) and argv[idx + 1].isdigit():
            min_batches = int(argv[idx + 1])
    require_device, device_tol = _optional_float(argv, "--require-device")
    try:
        with open(path) as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        print(f"{path}: unreadable trace: {e}", file=sys.stderr)
        return 1
    errors = validate(trace)
    if not errors and require_pipeline:
        errors = validate_pipeline(trace, min_batches)
    if not errors and require_device:
        errors = validate_device_merge(trace, tolerance_us=device_tol)
    for err in errors:
        print(f"{path}: {err}", file=sys.stderr)
    if not errors:
        n_events = len(trace.get("traceEvents", trace) if isinstance(trace, dict) else trace)
        print(f"{path}: OK ({n_events} events)")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
