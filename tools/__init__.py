"""Standalone operator/CI tools (lint, trace/metrics checkers, bundle
triage, firehose load harness).  A package so bench.py and tests can
import the harness pieces (``tools.firehose``) in-process; every module
here remains directly runnable as a script."""
