#!/usr/bin/env python3
"""Invariant lint driver: run every static-analysis layer, exit nonzero on
violations.

Layers (see lodestar_tpu/analysis/ and docs/static_analysis.md):

1. AST lint over lodestar_tpu/ (async hot-path discipline, tracing
   clock discipline, lock-hold discipline, metrics coverage).
2. Compile-cost audit: stdlib AST + import graph over tests/ and tools/
   proving which tier-1 tests materialize device programs, cross-checked
   against .jax_cache/tier1_timings.json and the conftest compile-guard
   whitelist (rules compile-unstubbed-test, compile-duplicate-program,
   compile-whitelist-stale, tier2-unmarked).
3. Lock/race audit: instrumented-lock interleaving harness over
   BlsBatchPool._flush -> TpuBlsVerifier.dispatch -> DeviceExecutor.
4. Jaxpr auditor: abstract traces of every public fused entry point in
   lodestar_tpu/ops/ at two bucket sizes (make_jaxpr only — CPU-safe, no
   device programs; ~2 min cold, then incremental: per-entry artifacts
   are cached under .jax_cache/ keyed by a content hash of ops/, so
   re-runs on an untouched ops/ replay in milliseconds) plus the
   limb-interval overflow proof over the ops/limbs.py contracts.
5. Pallas kernel verifier: every pallas_call in the traced entries plus
   the kernel library (pallas_tower / pallas_fuse / pallas_ring) is
   audited for DMA/semaphore balance, ref races, ring-neighbor
   topology, and Mosaic block tiling (rules pallas-dma-unbalanced,
   pallas-ref-race, pallas-ring-neighbor, pallas-block-misaligned) —
   rides the same artifact cache as layer 4.

Usage:
    python tools/lint.py [--repo PATH] [--json] [--skip-jaxpr]
                         [--skip-lock-audit] [--skip-compile-cost]
                         [--skip-pallas] [--buckets 4,128] [--rules]

Exit 0 when clean; exit 1 listing the violations.  tier-1 drives the same
layers from tests/test_static_analysis.py; bench.py runs this as a
pre-flight stage and records violations in extras.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_REPO_DEFAULT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_DEFAULT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # tracing never needs a TPU
# the sharded-entry audit needs >= 2 devices at trace time (shard_map
# binds mesh devices); force the tier-1 virtual-device shape so a
# standalone lint builds the SAME artifacts the suite replays
if "jax" not in sys.modules:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

from lodestar_tpu.analysis import format_report, run_all  # noqa: E402,F401
from lodestar_tpu.analysis.report import to_dicts  # noqa: E402


def _print_rules() -> None:
    from lodestar_tpu.analysis.ast_lint import DEFAULT_CHECKERS, MetricsCoverageChecker

    rows = [(c.rule, c.description) for c in DEFAULT_CHECKERS]
    rows.append((MetricsCoverageChecker.rule, MetricsCoverageChecker.description))
    rows += [
        ("lock-unguarded-mutation", "shared hot-path state mutated without its lock"),
        ("lock-order-inversion", "cycle in the lock acquisition graph"),
        ("jaxpr-narrow-mixed-concat", "Mosaic-unretileable splice (BENCH_r05 class)"),
        ("jaxpr-f64-leak", "64-bit dtype outside the f32 limb format"),
        ("jaxpr-host-callback", "host callback inside a hot-path program"),
        ("jaxpr-unstable-cache-key", "captured scalar / bucket-dependent constants"),
        ("jaxpr-mxu-precision", "dot_general without f32 preferred type + HIGHEST"),
        ("jaxpr-limb-overflow", "limb digit magnitude proven past the f32-exact 2^24"),
        ("pallas-dma-unbalanced", "DMA start/wait semaphore imbalance on some control path"),
        ("pallas-ref-race", "Ref slice touched while a DMA is in flight (slot aliasing)"),
        ("pallas-ring-neighbor", "remote device id not congruent mod axis size / self-send"),
        ("pallas-block-misaligned", "gridded block splits a Mosaic tile or operand raggedly"),
        ("compile-unstubbed-test", "tier-1 test reaches a real verifier materialization"),
        ("compile-duplicate-program", "two tier-1 modules materialize the same program key"),
        ("compile-whitelist-stale", "compile-guard whitelist entry covers no compiling test"),
        ("tier2-unmarked", "compile-bound test missing the slow marker"),
    ]
    width = max(len(r) for r, _ in rows)
    for rule, desc in rows:
        print(f"{rule:<{width}}  {desc}")


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=_REPO_DEFAULT)
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--skip-jaxpr", action="store_true",
                    help="skip the (slow) jaxpr IR audit")
    ap.add_argument("--skip-lock-audit", action="store_true",
                    help="skip the lock/race interleaving harness")
    ap.add_argument("--skip-compile-cost", action="store_true",
                    help="skip the compile-cost static audit of tests/")
    ap.add_argument("--skip-pallas", action="store_true",
                    help="skip the Pallas kernel verifier layer")
    ap.add_argument("--buckets", default="4,128",
                    help="comma-separated bucket sizes for the jaxpr audit")
    ap.add_argument("--no-trace-cache", action="store_true",
                    help="ignore the .jax_cache/ artifact cache and re-trace "
                    "every entry point (the cache self-invalidates on any "
                    "ops/ edit; this flag forces it)")
    ap.add_argument("--rules", action="store_true", help="list the rule catalogue")
    args = ap.parse_args(argv)
    if args.rules:
        _print_rules()
        return 0
    buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    violations = run_all(
        repo=args.repo,
        buckets=buckets,
        with_jaxpr=not args.skip_jaxpr,
        with_lock_audit=not args.skip_lock_audit,
        trace_cache=not args.no_trace_cache,
        with_compile_cost=not args.skip_compile_cost,
        with_pallas=not args.skip_pallas,
    )
    if args.json:
        print(json.dumps({"violations": to_dicts(violations)}, indent=2))
    else:
        print(format_report(violations))
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
