"""Generate a minimal-preset conformance-vector tree in the OFFICIAL
ethereum/consensus-spec-tests directory format.

Why self-generated vectors exist (VERDICT r3 item 8): this image has zero
egress, so the official tarball cannot be fetched.  These vectors:
  1. exercise every wired category of the spec-test harness end-to-end
     (directory layout, ssz_snappy codec, yaml metas, coverage check),
  2. pin today's behavior against regressions (any STF change that
     shifts a state root fails the suite),
  3. keep tests/test_spec_vectors.py byte-compatible with the official
     tree — drop ethereum/consensus-spec-tests at spec-tests/ and the
     same runners consume it unchanged.
They are NOT independent conformance evidence; tests/test_spec_harness.py
and the hand-pinned KATs carry that role until the official vectors can
be vendored.

Layout: spec-tests/tests/minimal/<fork>/<runner>/<handler>/<suite>/<case>/
Run: python tools/gen_spec_vectors.py    (idempotent; output committed)
"""

from __future__ import annotations

import asyncio
import os
import shutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import yaml  # noqa: E402

from lodestar_tpu.chain.bls_pool import BlsBatchPool  # noqa: E402
from lodestar_tpu.config.chain_config import ChainConfig  # noqa: E402
from lodestar_tpu.crypto.bls.native_verifier import FastBlsVerifier  # noqa: E402
from lodestar_tpu.node.dev_chain import DevChain, clone_state  # noqa: E402
from lodestar_tpu.params import MINIMAL  # noqa: E402
from lodestar_tpu.ssz import Fields  # noqa: E402
from lodestar_tpu.state_transition import (  # noqa: E402
    EpochContext,
    process_slots,
    state_transition,
)
from lodestar_tpu.types import get_types  # noqa: E402
from lodestar_tpu.utils.snappy import frame_compress  # noqa: E402

ROOT = os.path.join(os.path.dirname(__file__), "..", "spec-tests", "tests", "minimal")
T = get_types(MINIMAL)

CFG = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)
CFG_ALTAIR = ChainConfig(
    PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
    ALTAIR_FORK_EPOCH=1, BELLATRIX_FORK_EPOCH=2**64 - 1,
)


def case_dir(
    fork: str, runner: str, handler: str, suite: str, name: str,
    config: str = "minimal",
) -> str:
    base = ROOT if config == "minimal" else os.path.join(
        os.path.dirname(ROOT), config
    )
    d = os.path.join(base, fork, runner, handler, suite, name)
    os.makedirs(d, exist_ok=True)
    return d


def write_ssz(d: str, stem: str, data: bytes) -> None:
    with open(os.path.join(d, f"{stem}.ssz_snappy"), "wb") as f:
        f.write(frame_compress(data))


def write_yaml(d: str, stem: str, obj) -> None:
    with open(os.path.join(d, f"{stem}.yaml"), "w") as f:
        yaml.safe_dump(obj, f)


def state_bytes(fork: str, state) -> bytes:
    return getattr(T, fork).BeaconState.serialize(state)


def block_bytes(fork: str, signed) -> bytes:
    return getattr(T, fork).SignedBeaconBlock.serialize(signed)


async def build_chain(cfg, slots: int) -> DevChain:
    pool = BlsBatchPool(FastBlsVerifier(), max_buffer_wait=0.001)
    dev = DevChain(MINIMAL, cfg, 16, pool)
    await dev.run(slots)
    return dev


def canonical_blocks(dev: DevChain, lo: int, hi: int):
    out = []
    for slot in range(lo, hi + 1):
        root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, slot)
        blk = dev.chain.get_block_by_root(root) if root else None
        if blk is not None and blk.message.slot == slot:
            out.append(blk)
    return out


def gen_sanity_and_finality(dev: DevChain) -> None:
    # sanity/blocks: apply 2 blocks
    pre = clone_state(MINIMAL, dev.chain.genesis_state)
    blocks = canonical_blocks(dev, 1, 2)
    post = clone_state(MINIMAL, pre)
    for b in blocks:
        post, _ = state_transition(
            MINIMAL, CFG, post, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    d = case_dir("phase0", "sanity", "blocks", "pyspec_tests", "two_blocks")
    write_ssz(d, "pre", state_bytes("phase0", pre))
    for i, b in enumerate(blocks):
        write_ssz(d, f"blocks_{i}", block_bytes("phase0", b))
    write_ssz(d, "post", state_bytes("phase0", post))
    write_yaml(d, "meta", {"blocks_count": len(blocks)})

    # sanity/slots: cross an epoch boundary blockless
    pre2 = clone_state(MINIMAL, post)
    post2 = clone_state(MINIMAL, pre2)
    n_slots = MINIMAL.SLOTS_PER_EPOCH
    process_slots(MINIMAL, CFG, post2, post2.slot + n_slots)
    d = case_dir("phase0", "sanity", "slots", "pyspec_tests", "over_epoch_boundary")
    write_ssz(d, "pre", state_bytes("phase0", pre2))
    write_ssz(d, "post", state_bytes("phase0", post2))
    write_yaml(d, "slots", n_slots)

    # finality/finality: full epochs until finalization advances
    anchor_slot = 2 * MINIMAL.SLOTS_PER_EPOCH
    pre3_root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, anchor_slot)
    pre3 = clone_state(MINIMAL, dev.chain.get_state_by_block_root(pre3_root))
    blocks3 = canonical_blocks(dev, pre3.slot + 1, pre3.slot + 2 * MINIMAL.SLOTS_PER_EPOCH)
    post3 = clone_state(MINIMAL, pre3)
    for b in blocks3:
        post3, _ = state_transition(
            MINIMAL, CFG, post3, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    assert post3.finalized_checkpoint.epoch > pre3.finalized_checkpoint.epoch, (
        "finality vector must actually finalize"
    )
    d = case_dir("phase0", "finality", "finality", "pyspec_tests", "two_epochs_finalize")
    write_ssz(d, "pre", state_bytes("phase0", pre3))
    for i, b in enumerate(blocks3):
        write_ssz(d, f"blocks_{i}", block_bytes("phase0", b))
    write_ssz(d, "post", state_bytes("phase0", post3))
    write_yaml(d, "meta", {"blocks_count": len(blocks3)})


def gen_epoch_processing(dev: DevChain) -> None:
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        process_effective_balance_updates,
        process_justification_and_finalization,
        process_registry_updates,
        process_rewards_and_penalties,
        process_slashings,
    )

    # a state at the last slot of an epoch, mid-chain (has attestations)
    slot = 3 * MINIMAL.SLOTS_PER_EPOCH - 1
    root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, slot)
    base = clone_state(MINIMAL, dev.chain.get_state_by_block_root(root))
    if base.slot < slot:
        process_slots(MINIMAL, CFG, base, slot)
    ctx = EpochContext.create_from_state(MINIMAL, base)

    def sub_case(handler: str, fn) -> None:
        pre = clone_state(MINIMAL, base)
        post = clone_state(MINIMAL, pre)
        pctx = EpochContext.create_from_state(MINIMAL, post)
        flags = before_process_epoch(MINIMAL, pctx, post)
        fn(post, flags)
        d = case_dir("phase0", "epoch_processing", handler, "pyspec_tests", "mid_chain")
        write_ssz(d, "pre", state_bytes("phase0", pre))
        write_ssz(d, "post", state_bytes("phase0", post))

    sub_case(
        "justification_and_finalization",
        lambda st, fl: process_justification_and_finalization(MINIMAL, st, fl),
    )
    sub_case(
        "rewards_and_penalties",
        lambda st, fl: process_rewards_and_penalties(MINIMAL, CFG, st, fl),
    )
    sub_case("registry_updates", lambda st, fl: process_registry_updates(MINIMAL, CFG, st))
    sub_case("slashings", lambda st, fl: process_slashings(MINIMAL, st, fl))
    sub_case(
        "effective_balance_updates",
        lambda st, fl: process_effective_balance_updates(MINIMAL, st),
    )


def gen_operations(dev: DevChain) -> None:
    from lodestar_tpu.state_transition.block import (
        process_attestation,
        process_block_header,
    )

    # operations/attestation: a block's first attestation applied alone
    for slot in range(2, 4 * MINIMAL.SLOTS_PER_EPOCH):
        root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, slot)
        blk = dev.chain.get_block_by_root(root) if root else None
        if blk is not None and blk.message.slot == slot and len(blk.message.body.attestations):
            parent_state = clone_state(
                MINIMAL, dev.chain.get_state_by_block_root(bytes(blk.message.parent_root))
            )
            ctx = process_slots(MINIMAL, CFG, parent_state, slot)
            att = blk.message.body.attestations[0]
            pre = clone_state(MINIMAL, parent_state)
            post = clone_state(MINIMAL, pre)
            process_attestation(MINIMAL, ctx, post, att, False)
            d = case_dir("phase0", "operations", "attestation", "pyspec_tests", "from_block")
            write_ssz(d, "pre", state_bytes("phase0", pre))
            write_ssz(d, "attestation", T.phase0.Attestation.serialize(att))
            write_ssz(d, "post", state_bytes("phase0", post))
            # invalid: inclusion-delay violation (attestation from this
            # very slot); no post file => the runner must see a failure
            bad = T.phase0.Attestation.deserialize(T.phase0.Attestation.serialize(att))
            bad.data.slot = pre.slot
            d = case_dir(
                "phase0", "operations", "attestation", "pyspec_tests",
                "invalid_future_slot",
            )
            write_ssz(d, "pre", state_bytes("phase0", pre))
            write_ssz(d, "attestation", T.phase0.Attestation.serialize(bad))
            break

    # operations/block_header
    slot = 3
    root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, slot)
    blk = dev.chain.get_block_by_root(root)
    parent_state = clone_state(
        MINIMAL, dev.chain.get_state_by_block_root(bytes(blk.message.parent_root))
    )
    ctx = process_slots(MINIMAL, CFG, parent_state, slot)
    pre = clone_state(MINIMAL, parent_state)
    post = clone_state(MINIMAL, pre)
    process_block_header(MINIMAL, ctx, post, blk.message)
    d = case_dir("phase0", "operations", "block_header", "pyspec_tests", "from_block")
    write_ssz(d, "pre", state_bytes("phase0", pre))
    write_ssz(d, "block", T.phase0.BeaconBlock.serialize(blk.message))
    write_ssz(d, "post", state_bytes("phase0", post))


def gen_transition(dev_altair: DevChain) -> None:
    """fork/ (upgrade function) + transition/ (blocks across the fork)."""
    # fork/fork: the pure upgrade on the epoch-1 boundary state
    from lodestar_tpu.state_transition.upgrade import upgrade_state_to_altair

    boundary_slot = MINIMAL.SLOTS_PER_EPOCH
    root = dev_altair.chain.fork_choice.proto.get_ancestor(
        dev_altair.chain.head_root, boundary_slot - 1
    )
    pre_state = clone_state(MINIMAL, dev_altair.chain.get_state_by_block_root(root))
    # advance to the boundary WITHOUT the fork config applying the upgrade
    process_slots(MINIMAL, CFG, pre_state, boundary_slot)
    pre = clone_state(MINIMAL, pre_state)
    ctx = EpochContext.create_from_state(MINIMAL, pre_state)
    upgrade_state_to_altair(MINIMAL, CFG_ALTAIR, ctx, pre_state)  # in place
    post = pre_state
    d = case_dir("altair", "fork", "fork", "pyspec_tests", "epoch1_upgrade")
    write_ssz(d, "pre", state_bytes("phase0", pre))
    write_ssz(d, "post", state_bytes("altair", post))
    write_yaml(d, "meta", {"fork": "altair"})

    # transition/core: blocks crossing the fork boundary
    genesis = clone_state(MINIMAL, dev_altair.chain.genesis_state)
    blocks = canonical_blocks(dev_altair, 1, 2 * MINIMAL.SLOTS_PER_EPOCH)
    post_t = clone_state(MINIMAL, genesis)
    for b in blocks:
        post_t, _ = state_transition(
            MINIMAL, CFG_ALTAIR, post_t, b, verify_proposer_signature=False,
            verify_signatures=False, verify_state_root=True,
        )
    d = case_dir("altair", "transition", "core", "pyspec_tests", "through_altair_fork")
    write_ssz(d, "pre", state_bytes("phase0", genesis))
    for i, b in enumerate(blocks):
        fork = "phase0" if b.message.slot < MINIMAL.SLOTS_PER_EPOCH else "altair"
        write_ssz(d, f"blocks_{i}", block_bytes(fork, b))
    write_ssz(d, "post", state_bytes("altair", post_t))
    write_yaml(
        d, "meta",
        {"post_fork": "altair", "fork_epoch": 1, "blocks_count": len(blocks)},
    )


def gen_ssz_static_and_shuffling(dev: DevChain) -> None:
    state = dev.chain.head_state()
    samples = {
        "Checkpoint": (T.phase0.Checkpoint, state.finalized_checkpoint),
        "Fork": (T.phase0.Fork, state.fork),
        "Validator": (T.phase0.Validator, state.validators[0]),
        "BeaconBlockHeader": (T.phase0.BeaconBlockHeader, state.latest_block_header),
        "AttestationData": (
            T.phase0.AttestationData,
            state.previous_epoch_attestations[0].data
            if len(state.previous_epoch_attestations)
            else None,
        ),
        "Eth1Data": (T.phase0.Eth1Data, state.eth1_data),
        "BeaconState": (T.phase0.BeaconState, state),
    }
    for name, (typ, value) in samples.items():
        if value is None:
            continue
        d = case_dir("phase0", "ssz_static", name, "ssz_random", "case_0")
        ser = typ.serialize(value)
        write_ssz(d, "serialized", ser)
        write_yaml(d, "roots", {"root": "0x" + typ.hash_tree_root(value).hex()})

    # shuffling: the official mapping format; cross-checks the scalar
    # compute_shuffled_index against the vectorized unshuffle (two
    # independent in-repo implementations)
    import numpy as np

    from lodestar_tpu.state_transition.shuffle import unshuffle_list

    seed = bytes(range(32))
    for count in (2, 17, 64):
        shuffled = unshuffle_list(
            np.arange(count, dtype=np.int64), seed, MINIMAL.SHUFFLE_ROUND_COUNT
        )
        # official semantics: mapping[i] = shuffled position of index i
        d = case_dir("phase0", "shuffling", "core", "shuffle", f"shuffle_0x{seed[:4].hex()}_{count}")
        write_yaml(
            d, "mapping",
            {
                "seed": "0x" + seed.hex(),
                "count": count,
                "mapping": [int(x) for x in shuffled],
            },
        )


def _altair_epoch_fns():
    from lodestar_tpu.state_transition.altair import (
        process_inactivity_updates,
        process_justification_and_finalization_altair,
        process_participation_flag_updates,
        process_rewards_and_penalties_altair,
        process_slashings_altair,
        process_sync_committee_updates,
    )

    return {
        "justification_and_finalization": lambda st: process_justification_and_finalization_altair(MINIMAL, st),
        "inactivity_updates": lambda st: process_inactivity_updates(MINIMAL, CFG_ALTAIR, st),
        "rewards_and_penalties": lambda st: process_rewards_and_penalties_altair(MINIMAL, CFG_ALTAIR, st),
        "slashings": lambda st: process_slashings_altair(MINIMAL, st),
        "participation_flag_updates": lambda st: process_participation_flag_updates(st),
        "sync_committee_updates": lambda st: process_sync_committee_updates(MINIMAL, st),
    }


def gen_epoch_processing_altair(dev_altair: DevChain) -> None:
    """Altair epoch_processing sub-cases (the altair-specific handlers:
    inactivity/participation-flag/sync-committee updates).

    The base state sits at the LAST slot before a sync-committee-period
    boundary (next_epoch % EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0) so the
    rotation actually fires, at an epoch >= 2 so altair justification
    runs, and is perturbed with a slashed validator + nonzero inactivity
    scores so those handlers do real work — an identity pre==post vector
    pins nothing."""
    base = clone_state(MINIMAL, dev_altair.chain.head_state())
    period_epochs = MINIMAL.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    target_slot = period_epochs * MINIMAL.SLOTS_PER_EPOCH - 1
    if base.slot < target_slot:
        process_slots(MINIMAL, CFG_ALTAIR, base, target_slot)
    # perturbations: a slashed validator mid-withdrawal window (altair
    # slashings penalty applies at withdrawable - VECTOR/2) + inactivity
    current_epoch = target_slot // MINIMAL.SLOTS_PER_EPOCH
    v = base.validators[5]
    v.slashed = True
    # penalty applies when withdrawable == epoch + VECTOR/2 (spec
    # process_slashings; the handler reads the epoch of state.slot)
    v.withdrawable_epoch = current_epoch + MINIMAL.EPOCHS_PER_SLASHINGS_VECTOR // 2
    base.slashings[current_epoch % MINIMAL.EPOCHS_PER_SLASHINGS_VECTOR] = (
        v.effective_balance
    )
    scores = list(base.inactivity_scores)
    scores[3] = 7
    scores[7] = 12
    base.inactivity_scores = scores
    for handler, fn in _altair_epoch_fns().items():
        pre = clone_state(MINIMAL, base)
        post = clone_state(MINIMAL, pre)
        fn(post)
        assert state_bytes("altair", post) != state_bytes("altair", pre), (
            f"identity altair epoch_processing vector pins nothing: {handler}"
        )
        d = case_dir("altair", "epoch_processing", handler, "pyspec_tests", "mid_chain")
        write_ssz(d, "pre", state_bytes("altair", pre))
        write_ssz(d, "post", state_bytes("altair", post))


def _deltas_type():
    from lodestar_tpu.ssz import Container, List, uint64

    return Container(
        "Deltas",
        [
            ("rewards", List(uint64, MINIMAL.VALIDATOR_REGISTRY_LIMIT)),
            ("penalties", List(uint64, MINIMAL.VALIDATOR_REGISTRY_LIMIT)),
        ],
    )


def gen_rewards(dev: DevChain) -> None:
    """rewards/basic: the five per-component delta files the official
    vectors pin (presets/rewards.ts)."""
    from lodestar_tpu.state_transition.epoch import (
        before_process_epoch,
        get_attestation_component_deltas,
    )

    slot = 3 * MINIMAL.SLOTS_PER_EPOCH - 1
    root = dev.chain.fork_choice.proto.get_ancestor(dev.chain.head_root, slot)
    pre = clone_state(MINIMAL, dev.chain.get_state_by_block_root(root))
    if pre.slot < slot:
        process_slots(MINIMAL, CFG, pre, slot)
    ctx = EpochContext.create_from_state(MINIMAL, pre)
    flags = before_process_epoch(MINIMAL, ctx, pre)
    components = get_attestation_component_deltas(MINIMAL, CFG, pre, flags)
    dt = _deltas_type()
    d = case_dir("phase0", "rewards", "basic", "pyspec_tests", "mid_chain")
    write_ssz(d, "pre", state_bytes("phase0", pre))
    names = {
        "source": "source_deltas", "target": "target_deltas",
        "head": "head_deltas", "inclusion_delay": "inclusion_delay_deltas",
        "inactivity": "inactivity_penalty_deltas",
    }
    for key, stem in names.items():
        rewards, penalties = components[key]
        write_ssz(
            d, stem,
            dt.serialize(Fields(rewards=[int(x) for x in rewards],
                                penalties=[int(x) for x in penalties])),
        )

    # rewards/leak: finality stalled past MIN_EPOCHS_TO_INACTIVITY_PENALTY
    # (blockless slots from genesis), exercising the is_inactivity_leak
    # branch of every component
    leak_pre = clone_state(MINIMAL, dev.chain.genesis_state)
    leak_slot = (MINIMAL.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 3) * MINIMAL.SLOTS_PER_EPOCH - 1
    process_slots(MINIMAL, CFG, leak_pre, leak_slot)
    lctx = EpochContext.create_from_state(MINIMAL, leak_pre)
    lflags = before_process_epoch(MINIMAL, lctx, leak_pre)
    lcomponents = get_attestation_component_deltas(MINIMAL, CFG, leak_pre, lflags)
    # the inactivity component penalizes ONLY when is_inactivity_leak —
    # the one signal that proves the leak branch actually fired
    assert lcomponents["inactivity"][1].any(), "leak case must hit the leak branch"
    d = case_dir("phase0", "rewards", "leak", "pyspec_tests", "stalled_finality")
    write_ssz(d, "pre", state_bytes("phase0", leak_pre))
    for key, stem in names.items():
        rewards, penalties = lcomponents[key]
        write_ssz(
            d, stem,
            dt.serialize(Fields(rewards=[int(x) for x in rewards],
                                penalties=[int(x) for x in penalties])),
        )


def gen_genesis() -> None:
    """genesis/initialization + genesis/validity (official format:
    eth1.yaml, deposits_<i>.ssz_snappy, meta.yaml, expected state;
    validity cases carry genesis.ssz_snappy + is_valid.yaml)."""
    from lodestar_tpu.spec_test_util.deposits import build_deposits
    from lodestar_tpu.state_transition.genesis import (
        initialize_beacon_state_from_eth1,
        is_valid_genesis_state,
    )

    # the REAL minimal chain config: official vectors sign deposits over
    # GENESIS_FORK_VERSION 0x00000001 and judge validity at 64 validators,
    # so anything else would make the runner non-conformant
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG as gcfg

    deposits = build_deposits(MINIMAL, gcfg, gcfg.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT)
    eth1_block_hash = b"\x12" * 32
    eth1_timestamp = gcfg.MIN_GENESIS_TIME
    state = initialize_beacon_state_from_eth1(
        MINIMAL, gcfg, eth1_block_hash, eth1_timestamp, deposits
    )
    d = case_dir("phase0", "genesis", "initialization", "pyspec_tests", "case_0")
    write_yaml(d, "eth1", {
        "eth1_block_hash": "0x" + eth1_block_hash.hex(),
        "eth1_timestamp": eth1_timestamp,
    })
    write_yaml(d, "meta", {"deposits_count": len(deposits)})
    for i, dep in enumerate(deposits):
        write_ssz(d, f"deposits_{i}", T.phase0.Deposit.serialize(dep))
    write_ssz(d, "state", state_bytes("phase0", state))

    for name, st, valid in (
        ("valid_genesis", state, True),
        (
            "invalid_too_few",
            initialize_beacon_state_from_eth1(
                MINIMAL, gcfg, eth1_block_hash, eth1_timestamp,
                build_deposits(MINIMAL, gcfg, 2),
            ),
            False,
        ),
    ):
        d = case_dir("phase0", "genesis", "validity", "pyspec_tests", name)
        write_ssz(d, "genesis", state_bytes("phase0", st))
        write_yaml(d, "is_valid", valid)


def gen_merkle(dev: DevChain) -> None:
    """merkle/single_proof: a state-field branch in the official
    proof.yaml shape (leaf, generalized leaf_index, branch)."""
    state = dev.chain.head_state()
    st_type = T.phase0.BeaconState
    for field in ("finalized_checkpoint", "validators"):
        leaf, branch = st_type.get_field_proof(state, field)
        nfields = len(st_type.fields)
        npow2 = 1
        while npow2 < nfields:
            npow2 *= 2
        idx = next(i for i, (f, _) in enumerate(st_type.fields) if f == field)
        d = case_dir("phase0", "merkle", "single_proof", "pyspec_tests", field)
        write_ssz(d, "state", state_bytes("phase0", state))
        write_yaml(d, "proof", {
            "leaf": "0x" + bytes(leaf).hex(),
            "leaf_index": npow2 + idx,
            "branch": ["0x" + bytes(b).hex() for b in branch],
        })


async def gen_fork_choice() -> None:
    """fork_choice/on_block step vectors: anchor + blocks + ticks +
    head/finality checks, including a competing-fork scenario (two
    chains from one genesis; the vector replays A's then B's blocks and
    pins the head after each)."""
    a = await build_chain(CFG, 0)
    b = await build_chain(CFG, 0)  # same interop genesis -> same anchor
    spe = MINIMAL.SLOTS_PER_EPOCH
    # A: attested canonical chain (advance_slot packs attestations into
    # the next block, so the replayed blocks carry LMD weight)
    for slot in range(1, spe + 3):
        await a.advance_slot(slot)
    blocks_a = canonical_blocks(a, 1, spe + 2)
    # B diverges: skips slot 1, builds a shorter unattested fork
    blocks_b = []
    for slot in range(2, spe):
        blocks_b.append(await b.produce_and_import_block(slot))

    d = case_dir("phase0", "fork_choice", "on_block", "pyspec_tests", "chain_with_fork")
    anchor = a.chain.genesis_state
    write_ssz(d, "anchor_state", state_bytes("phase0", anchor))
    anchor_block = Fields(
        slot=0, proposer_index=0, parent_root=b"\x00" * 32,
        state_root=T.phase0.BeaconState.hash_tree_root(anchor),
        body=T.phase0.BeaconBlockBody.default(),
    )
    write_ssz(d, "anchor_block", T.phase0.BeaconBlock.serialize(anchor_block))
    steps = []
    genesis_time = int(anchor.genesis_time)
    for i, blk in enumerate(blocks_a + blocks_b):
        write_ssz(d, f"block_{i}", block_bytes("phase0", blk))
        steps.append({
            "tick": genesis_time + int(blk.message.slot) * CFG.SECONDS_PER_SLOT
        })
        steps.append({"block": f"block_{i}"})
    # the attested chain A must win over B's fork
    steps.append({
        "checks": {
            "head": {
                "slot": int(a.chain.head_state().slot),
                "root": "0x" + a.chain.head_root.hex(),
            },
        }
    })
    write_yaml(d, "steps", steps)


async def gen_fork_choice_on_attestation() -> None:
    """fork_choice/on_attestation: two competing one-block forks off
    genesis; LMD votes must flip the head to the attested fork once the
    proposer boost of the later block expires."""
    a = await build_chain(CFG, 0)
    b = await build_chain(CFG, 0)
    blk_a = await a.produce_and_import_block(1)   # A: block at slot 1
    a.attest(1)                                   # votes for A's block
    blk_b = await b.produce_and_import_block(2)   # B: slot 2 off genesis

    d = case_dir(
        "phase0", "fork_choice", "on_attestation", "pyspec_tests", "votes_flip_head"
    )
    anchor = a.chain.genesis_state
    write_ssz(d, "anchor_state", state_bytes("phase0", anchor))
    anchor_block = Fields(
        slot=0, proposer_index=0, parent_root=b"\x00" * 32,
        state_root=T.phase0.BeaconState.hash_tree_root(anchor),
        body=T.phase0.BeaconBlockBody.default(),
    )
    write_ssz(d, "anchor_block", T.phase0.BeaconBlock.serialize(anchor_block))
    genesis_time = int(anchor.genesis_time)
    steps = []
    for i, blk in enumerate((blk_a, blk_b)):
        write_ssz(d, f"block_{i}", block_bytes("phase0", blk))
        steps.append({"tick": genesis_time + int(blk.message.slot) * CFG.SECONDS_PER_SLOT})
        steps.append({"block": f"block_{i}"})
    # B's proposer boost makes it head at slot 2...
    root_b = T.phase0.BeaconBlock.hash_tree_root(blk_b.message)
    steps.append({"checks": {"head": {"slot": 2, "root": "0x" + bytes(root_b).hex()}}})
    # ...then slot advances (boost expires) and A's votes land
    steps.append({"tick": genesis_time + 3 * CFG.SECONDS_PER_SLOT})
    for i, att in enumerate(a.pending_attestations):
        write_ssz(d, f"attestation_{i}", T.phase0.Attestation.serialize(att))
        steps.append({"attestation": f"attestation_{i}"})
    root_a = T.phase0.BeaconBlock.hash_tree_root(blk_a.message)
    steps.append({"checks": {"head": {"slot": 1, "root": "0x" + bytes(root_a).hex()}}})
    write_yaml(d, "steps", steps)


async def main() -> None:
    top = os.path.dirname(ROOT)  # spec-tests/tests (all configs)
    if os.path.isdir(top):
        shutil.rmtree(top)
    dev = await build_chain(CFG, 4 * MINIMAL.SLOTS_PER_EPOCH + 2)
    assert dev.chain.fork_choice.store.finalized_checkpoint.epoch >= 1
    gen_sanity_and_finality(dev)
    gen_epoch_processing(dev)
    gen_operations(dev)
    gen_ssz_static_and_shuffling(dev)
    gen_rewards(dev)
    gen_genesis()
    gen_merkle(dev)
    await gen_fork_choice()
    await gen_fork_choice_on_attestation()
    dev_altair = await build_chain(CFG_ALTAIR, MINIMAL.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * MINIMAL.SLOTS_PER_EPOCH - 1)
    gen_transition(dev_altair)
    gen_epoch_processing_altair(dev_altair)
    # breadth: altair/bellatrix categories, operation coverage, ssz depth,
    # mainnet tree (tools/gen_spec_vectors2.py)
    import gen_spec_vectors2

    await gen_spec_vectors2.generate(dev, dev_altair)
    n = sum(len(files) for _, _, files in os.walk(top))
    print(f"wrote {n} files under {os.path.abspath(top)}")


if __name__ == "__main__":
    asyncio.run(main())
