#!/usr/bin/env python3
"""Chaos campaign: every fault class, one live pool, zero undiagnosable deaths.

Runs a seeded, deterministic campaign of the full fault taxonomy
(docs/chaos.md) against a live ``BlsBatchPool`` → ``TpuBlsVerifier``
(stub device programs — the scheduler, health machine, requeue path,
forensics and accounting are all host-side; no XLA work) and asserts the
ROADMAP item-5 guarantee per fault class:

- **diagnosable**: every induced fault yields a diagnostic bundle that
  ``tools/inspect_bundle.py`` validates (watchdog stall, quarantine
  entry, native-tier degrade, salvage heartbeat, ...);
- **nothing lost**: every submitted verification job resolves — a real
  verdict or a typed ``VerificationDroppedError``; ``verdicts_lost``
  (stranded futures) must be 0 (PR 6's accounting identity, now under
  injected faults);
- **self-healing**: the failing executor is quarantined, re-admitted
  after its backoff probe, and post-fault throughput recovers to within
  10% of the pre-fault baseline.

Scenarios (all driven from ONE seed; repro = rerun with the same seed):

    device_loss     result() raises on one executor, twice -> requeue,
                    quarantine, probe re-admission, trace passes
                    check_trace --require-pipeline with bls.requeue spans
    device_wedge    result() blocks past the watchdog deadline ->
                    watchdog bundle naming cid+device, then recovery
    compile_ladder  fused AND XLA program calls fail -> the full
                    fused->XLA->native ladder, one degrade event per hop
    cache_corrupt   persistent compile-ledger file corrupted on disk ->
                    survivable + journaled (cache.corrupt)
    aot_corrupt     durable AOT executable store faults (docs/aot.md):
                    corrupt entry -> aot.corrupt + quarantine; truncated
                    manifest -> survivable; jax-version skew -> aot.skew
                    + eviction; prewarmer SIGKILLed mid-write -> orphan
                    temp ignored, manifest consistent, stale lock broken;
                    the live pool then still verifies (recompile)
    bench_kill      spawn child SIGKILLed mid-stage -> salvage heartbeat
                    bundle recovered pid-scoped by the parent
    forensics_io    bundle section writer raises -> per-section isolation
                    (error in manifest, bundle still valid)

Usage:
    python tools/chaos_campaign.py --seed 0
    python tools/chaos_campaign.py --seed 7 --json
    python bench.py        # runs this as the `chaos` stage

Exit 0 when every scenario holds; 1 otherwise (failures listed).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import multiprocessing
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# the stub executors need >= 4 virtual CPU devices; must be set before
# the first jax import (a no-op when the host already forces them, e.g.
# under tests/conftest.py or the bench multichip stage)
if "jax" not in sys.modules:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

from lodestar_tpu import tracing  # noqa: E402
from lodestar_tpu.chaos import (  # noqa: E402
    CHAOS,
    FaultPlan,
    corrupt_file,
)
from lodestar_tpu.crypto.bls.verifier import (  # noqa: E402
    VerificationDroppedError,
)
from lodestar_tpu.forensics import salvage  # noqa: E402
from lodestar_tpu.forensics.bundle import latest_bundle  # noqa: E402
from lodestar_tpu.forensics.journal import JOURNAL  # noqa: E402
from lodestar_tpu.forensics.recorder import RECORDER  # noqa: E402


def load_tool(name: str):
    """Load a sibling tools/ script as a module (tools are CLIs first;
    this is the one file-loader the campaign and its tests share)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# stub pool construction (the test_multidevice_scheduler discipline: real
# verifier, real scheduler, real spans/journal/health — stub device programs)
# ---------------------------------------------------------------------------


class _SlowVerdict:
    """bool() blocks until ready_at — the device-readback stand-in."""

    def __init__(self, ready_at: float, value: bool = True):
        self._ready_at = ready_at
        self._value = value

    def __bool__(self) -> bool:
        rem = self._ready_at - time.monotonic()
        if rem > 0:
            time.sleep(rem)
        return self._value


class _StubNative:
    """Host-native tier stand-in for stub campaigns (the routing, events,
    and metrics are what the ladder scenario asserts — not the bigint
    pairing itself, which tools/firehose.py --verifier native covers)."""

    def __init__(self):
        self.calls = 0

    def verify_signature_sets(self, sets) -> bool:
        self.calls += 1
        return True

    def close(self) -> None:
        return None


def make_sets(n: int, start: int = 0, key_mod: int = 8) -> List[Any]:
    from lodestar_tpu.crypto.bls.api import interop_secret_key
    from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet

    out = []
    for i in range(start, start + n):
        sk = interop_secret_key(i % key_mod)
        msg = bytes([i % 256, (i // 256) % 256]) * 16
        out.append(
            SingleSignatureSet(
                pubkey=sk.to_public_key(),
                signing_root=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


def stub_verifier(n_devices: int = 4, device_s: float = 0.01,
                  backoff_s: float = 0.25, threshold: int = 2,
                  fused: bool = False, sharded: bool = False,
                  bucket: int = 4):
    """Real TpuBlsVerifier with stub device programs on every executor
    (and, when ``fused``, under the fused program key too so the ladder
    scenario has a working fused path to fail).  ``sharded`` stubs the
    mesh pseudo-executor as well, so the round-11 mesh tier routes for
    ``bucket``-sized merged batches with zero XLA work."""
    import jax

    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier

    local = jax.devices("cpu")
    devices = local[: min(n_devices, len(local))] if n_devices > 1 else None
    v = TpuBlsVerifier(
        buckets=(bucket,), devices=devices, fused=fused, host_final_exp=False,
        quarantine_threshold=threshold, quarantine_backoff_s=backoff_s,
        native_verifier=_StubNative(),
        sharded=sharded or None, sharded_min_batch=bucket if sharded else None,
    )
    for ex in v._executors:
        for key_fused in ((False, True) if fused else (False,)):
            ex.compiled[(bucket, False, key_fused)] = (
                lambda *a: _SlowVerdict(time.monotonic() + device_s)
            )
    if sharded:
        v._mesh_ex.compiled[(bucket, False, False)] = (
            lambda *a: _SlowVerdict(time.monotonic() + device_s)
        )
    return v


# ---------------------------------------------------------------------------
# job runner with full verdict accounting
# ---------------------------------------------------------------------------


async def run_jobs(pool, n_jobs: int, sets_per_job: int = 2,
                   spacing_s: float = 0.0, grace_s: float = 20.0) -> Dict[str, Any]:
    """Submit n_jobs and account for EVERY outcome.  ``verdicts_lost``
    is the stranded-future count — the number this whole campaign exists
    to keep at zero."""
    outcomes = {"ok": 0, "false": 0, "dropped": 0}
    errors: List[str] = []

    async def one(i: int) -> None:
        try:
            ok = await pool.verify_signature_sets(
                make_sets(sets_per_job, start=i * sets_per_job)
            )
            outcomes["ok" if ok else "false"] += 1
        except VerificationDroppedError:
            outcomes["dropped"] += 1
        except Exception as e:  # noqa: BLE001 — the harness accounts, never dies
            errors.append(f"{type(e).__name__}: {e}")

    t0 = time.monotonic()
    tasks = []
    for i in range(n_jobs):
        tasks.append(asyncio.create_task(one(i)))
        if spacing_s:
            await asyncio.sleep(spacing_s)
    done, pending = await asyncio.wait(tasks, timeout=grace_s)
    for t in pending:
        t.cancel()
    wall = time.monotonic() - t0
    return {
        "jobs": n_jobs,
        "outcomes": outcomes,
        "errors": errors,
        "verdicts_lost": len(pending),
        "sets_per_s": round(n_jobs * sets_per_job / wall, 1) if wall else None,
        "wall_s": round(wall, 3),
    }


def _journal_since(seq_floor: int) -> List[Dict[str, Any]]:
    return [e for e in JOURNAL.events() if e["seq"] >= seq_floor]


def _first(events: List[Dict[str, Any]],
           pred: Callable[[Dict[str, Any]], bool]) -> Optional[Dict[str, Any]]:
    for e in events:
        if pred(e):
            return e
    return None


async def _heal(pool, verifier, deadline_s: float = 8.0):
    """Keep offering light traffic until every executor is healthy again
    (the backoff probe needs real placements to ride).  Returns
    ``(healed, stats)`` — the probe traffic's own verdicts count toward
    the campaign accounting too (a future stranded DURING healing is
    still a stranded future)."""
    stats = {"verdicts_lost": 0, "false": 0, "errors": []}
    t_end = time.monotonic() + deadline_s

    def all_healthy() -> bool:
        return {h["state"] for h in verifier.executor_health().values()} == {"healthy"}

    while time.monotonic() < t_end:
        if all_healthy():
            return True, stats
        r = await run_jobs(pool, 2, spacing_s=0.0, grace_s=5.0)
        stats["verdicts_lost"] += r["verdicts_lost"]
        stats["false"] += r["outcomes"]["false"]
        stats["errors"] += r["errors"]
        await asyncio.sleep(0.05)
    return all_healthy(), stats


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _validated_bundle(inspect_bundle, bundle_dir: Optional[str],
                      result: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Validate one bundle and fold the outcome into the scenario result."""
    if not bundle_dir:
        result.setdefault("failures", []).append("no bundle written")
        return None
    errs = inspect_bundle.validate(bundle_dir)
    if errs:
        result.setdefault("failures", []).append(
            f"bundle {bundle_dir} invalid: {errs[:3]}"
        )
        return None
    result.setdefault("bundles", []).append(bundle_dir)
    return inspect_bundle.summarize(bundle_dir)


def scenario_device_loss(seed: int, out_dir: str, inspect_bundle,
                         check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "device_loss"}
    v = stub_verifier(backoff_s=0.25)
    from lodestar_tpu.chain.bls_pool import BlsBatchPool

    pool = BlsBatchPool(v, max_buffer_wait=0.002, flush_threshold=8,
                        pipeline_depth=2)
    RECORDER.configure(forensics_dir=out_dir, pool=pool, verifier=v)
    tracing.TRACER.clear()
    tracing.enable(16384)
    target = v._executors[1].name
    seq0 = JOURNAL.seq

    async def main():
        baseline = await run_jobs(pool, 8 if fast else 24)
        CHAOS.install(
            FaultPlan(seed).add("device.loss", match={"device": target}, count=2)
        )
        under_fault = await run_jobs(pool, 12 if fast else 24)
        healed, heal_stats = await _heal(pool, v)
        recovered = await run_jobs(pool, 8 if fast else 24)
        return baseline, under_fault, healed, heal_stats, recovered

    try:
        baseline, under_fault, healed, heal_stats, recovered = asyncio.run(main())
    finally:
        # a mid-scenario raise must not leak an armed plan, an open pool,
        # or an enabled tracer into the NEXT scenario's assertions
        CHAOS.disarm()
        pool.close()
        tracing.TRACER.disable()

    events = _journal_since(seq0)
    inject = _first(events, lambda e: e.get("kind") == "chaos.inject")
    quarantine = _first(
        events,
        lambda e: e.get("kind") == "bls.health"
        and e.get("state") == "quarantined" and e.get("device") == target,
    )
    readmit = _first(
        events,
        lambda e: e.get("kind") == "bls.health" and e.get("readmitted"),
    )
    requeues = [e for e in events if e.get("kind") == "bls.requeue"]

    res["baseline_sets_per_s"] = baseline["sets_per_s"]
    res["recovered_sets_per_s"] = recovered["sets_per_s"]
    res["verdicts_lost"] = (
        baseline["verdicts_lost"] + under_fault["verdicts_lost"]
        + heal_stats["verdicts_lost"] + recovered["verdicts_lost"]
    )
    res["errors"] = (
        baseline["errors"] + under_fault["errors"]
        + heal_stats["errors"] + recovered["errors"]
    )
    res["requeued_batches"] = len(requeues)
    failures: List[str] = []
    if res["verdicts_lost"]:
        failures.append(f"{res['verdicts_lost']} stranded futures")
    if res["errors"]:
        failures.append(f"untyped errors: {res['errors'][:3]}")
    false_verdicts = (
        baseline["outcomes"]["false"] + under_fault["outcomes"]["false"]
        + heal_stats["false"] + recovered["outcomes"]["false"]
    )
    if false_verdicts:
        failures.append("a lost device produced a False verdict")
    if not requeues:
        failures.append("no bls.requeue event — the failed batch was not requeued")
    if quarantine is None:
        failures.append(f"{target} was never quarantined")
    if readmit is None or not healed:
        failures.append(f"{target} was never re-admitted")
    if inject is not None and quarantine is not None:
        res["time_to_quarantine_s"] = round(
            (quarantine["ts_ns"] - inject["ts_ns"]) / 1e9, 3
        )
    if inject is not None and readmit is not None:
        res["time_to_recover_s"] = round(
            (readmit["ts_ns"] - inject["ts_ns"]) / 1e9, 3
        )
    if baseline["sets_per_s"] and recovered["sets_per_s"]:
        ratio = recovered["sets_per_s"] / baseline["sets_per_s"]
        res["throughput_recovery_ratio"] = round(ratio, 3)
        if ratio < 0.9:
            failures.append(
                f"throughput recovered to only {ratio:.0%} of baseline"
            )

    summary = _validated_bundle(
        inspect_bundle, latest_bundle(out_dir), res
    )
    if summary is not None:
        ch = summary.get("chaos") or {}
        if (ch.get("last_fault") or {}).get("seam") != "device.loss":
            failures.append("bundle chaos section missing the injected fault")

    # the requeued cid must still pass the pipeline gate (satellite:
    # check_trace accepts bls.requeue and demands the re-dispatch)
    trace_path = os.path.join(out_dir, "device_loss_trace.json")
    tracing.write_chrome_trace(tracing.TRACER, trace_path)
    if check_trace.main([trace_path, "--require-pipeline", "2"]) != 0:
        failures.append("trace with requeued batches failed --require-pipeline")
    res["trace"] = trace_path

    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def scenario_sharded_loss(seed: int, out_dir: str, inspect_bundle,
                          check_trace, fast: bool) -> Dict[str, Any]:
    """Round-11 acceptance class: device.loss DURING a mesh-spanning
    sharded batch.  The verdict must still resolve (same packed payload
    requeued onto one surviving executor — zero verdicts lost), the mesh
    health record must quarantine and later re-admit via the backoff
    probe, and the trace — mesh dispatch spans included — must pass
    check_trace's pipeline + mesh rules."""
    res: Dict[str, Any] = {"name": "sharded_loss"}
    v = stub_verifier(backoff_s=0.25, threshold=1, sharded=True, bucket=8)
    from lodestar_tpu.chain.bls_pool import BlsBatchPool

    pool = BlsBatchPool(v, max_buffer_wait=0.002, flush_threshold=8,
                        pipeline_depth=2)
    RECORDER.configure(forensics_dir=out_dir, pool=pool, verifier=v)
    tracing.TRACER.clear()
    tracing.enable(16384)
    target = v._mesh_ex.name
    seq0 = JOURNAL.seq

    async def main():
        baseline = await run_jobs(pool, 8 if fast else 16, sets_per_job=4)
        CHAOS.install(
            FaultPlan(seed).add("device.loss", match={"device": target},
                                count=1)
        )
        under_fault = await run_jobs(pool, 8 if fast else 16, sets_per_job=4)
        healed, heal_stats = await _heal(pool, v)
        recovered = await run_jobs(pool, 8 if fast else 16, sets_per_job=4)
        return baseline, under_fault, healed, heal_stats, recovered

    try:
        baseline, under_fault, healed, heal_stats, recovered = asyncio.run(main())
    finally:
        CHAOS.disarm()
        pool.close()
        tracing.TRACER.disable()

    events = _journal_since(seq0)
    quarantine = _first(
        events,
        lambda e: e.get("kind") == "bls.health"
        and e.get("state") == "quarantined" and e.get("device") == target,
    )
    readmit = _first(
        events,
        lambda e: e.get("kind") == "bls.health" and e.get("readmitted")
        and e.get("device") == target,
    )
    requeues = [
        e for e in events
        if e.get("kind") == "bls.requeue" and e.get("from_device") == target
    ]
    mesh_dispatches = [
        e for e in events
        if e.get("kind") == "bls.dispatch" and e.get("sharded")
    ]

    res["verdicts_lost"] = (
        baseline["verdicts_lost"] + under_fault["verdicts_lost"]
        + heal_stats["verdicts_lost"] + recovered["verdicts_lost"]
    )
    res["errors"] = (
        baseline["errors"] + under_fault["errors"]
        + heal_stats["errors"] + recovered["errors"]
    )
    res["mesh_batches"] = len(mesh_dispatches)
    res["requeued_batches"] = len(requeues)
    res["sharded_fallbacks"] = v.sharded_fallbacks
    failures: List[str] = []
    if res["verdicts_lost"]:
        failures.append(f"{res['verdicts_lost']} stranded futures")
    if res["errors"]:
        failures.append(f"untyped errors: {res['errors'][:3]}")
    false_verdicts = (
        baseline["outcomes"]["false"] + under_fault["outcomes"]["false"]
        + heal_stats["false"] + recovered["outcomes"]["false"]
    )
    if false_verdicts:
        failures.append("a lost mesh produced a False verdict")
    if not mesh_dispatches:
        failures.append("no sharded bls.dispatch — the mesh tier never engaged")
    if not any(e.get("mesh_devices", 0) >= 2 for e in mesh_dispatches):
        failures.append("sharded dispatch events missing mesh_devices >= 2")
    if not requeues:
        failures.append(
            "no bls.requeue from the mesh — the failed sharded batch "
            "was not replayed on a survivor"
        )
    if quarantine is None:
        failures.append(f"{target} was never quarantined")
    if readmit is None or not healed:
        failures.append(f"{target} was never re-admitted")
    if v.sharded is not True:
        failures.append(
            "sharded tier sticky-disabled by a SYNC fault — sync faults "
            "must ride the health machine, not the tier kill-switch"
        )

    # the mesh dump must pass the pipeline gate INCLUDING the new mesh
    # rules (mesh_devices present, devices_total honest)
    trace_path = os.path.join(out_dir, "sharded_loss_trace.json")
    tracing.write_chrome_trace(tracing.TRACER, trace_path)
    if check_trace.main([trace_path, "--require-pipeline", "2"]) != 0:
        failures.append("mesh trace failed --require-pipeline")
    res["trace"] = trace_path

    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def scenario_device_wedge(seed: int, out_dir: str, inspect_bundle,
                          check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "device_wedge"}
    v = stub_verifier(backoff_s=0.2, threshold=2)
    from lodestar_tpu.chain.bls_pool import BlsBatchPool

    pool = BlsBatchPool(v, max_buffer_wait=0.002, flush_threshold=8,
                        pipeline_depth=2)
    RECORDER.configure(forensics_dir=out_dir, pool=pool, verifier=v)
    RECORDER.start_watchdog(deadline_s=0.12, interval_s=0.04)
    target = v._executors[2].name
    seq0 = JOURNAL.seq
    CHAOS.install(
        FaultPlan(seed).add("device.wedge", match={"device": target},
                            count=1, wedge_s=0.45)
    )

    async def main():
        under_fault = await run_jobs(pool, 10 if fast else 20)
        healed, heal_stats = await _heal(pool, v)
        return under_fault, healed, heal_stats

    try:
        under_fault, healed, heal_stats = asyncio.run(main())
    finally:
        # never leak the 0.12s watchdog (or the pool) into later
        # scenarios — it would flag their normal in-flight batches and
        # write spurious bundles into their directories
        CHAOS.disarm()
        RECORDER.stop_watchdog()
        pool.close()

    events = _journal_since(seq0)
    stall = _first(events, lambda e: e.get("kind") == "watchdog.stall")
    failures: List[str] = []
    res["verdicts_lost"] = (
        under_fault["verdicts_lost"] + heal_stats["verdicts_lost"]
    )
    if res["verdicts_lost"]:
        failures.append(f"{res['verdicts_lost']} stranded futures")
    if under_fault["errors"] or heal_stats["errors"]:
        failures.append(
            f"untyped errors: {(under_fault['errors'] + heal_stats['errors'])[:3]}"
        )
    if stall is None:
        failures.append("watchdog never flagged the wedged batch")
    elif stall.get("device") != target:
        failures.append(
            f"watchdog named {stall.get('device')}, wedge was on {target}"
        )
    if not healed:
        failures.append("pool did not return to all-healthy")
    summary = _validated_bundle(inspect_bundle, latest_bundle(out_dir), res)
    if summary is not None and summary.get("reason") != "watchdog":
        # the newest bundle may be the quarantine/requeue one — find the
        # watchdog bundle explicitly
        watchdog_bundles = [
            os.path.join(out_dir, n) for n in os.listdir(out_dir)
            if n.startswith("bundle-watchdog")
        ]
        if not watchdog_bundles:
            failures.append("no watchdog bundle written for the wedge")
        else:
            _validated_bundle(inspect_bundle, watchdog_bundles[0], res)
    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def scenario_compile_ladder(seed: int, out_dir: str, inspect_bundle,
                            check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "compile_ladder"}
    from lodestar_tpu.metrics import create_metrics

    metrics = create_metrics()
    v = stub_verifier(n_devices=2, fused=True)
    v.metrics = metrics
    RECORDER.configure(forensics_dir=out_dir, verifier=v)
    seq0 = JOURNAL.seq
    CHAOS.install(
        FaultPlan(seed)
        .add("bls.compile", match={"where": "dispatch", "fused": True}, count=1)
        .add("bls.compile", match={"where": "dispatch", "fused": False}, count=1)
    )
    pend = v.verify_signature_sets_async(make_sets(2))
    verdict = pend.result()
    CHAOS.disarm()

    events = _journal_since(seq0)
    degrades = [e for e in events if e.get("kind") == "bls.degrade"]
    tiers = [e.get("tier") for e in degrades]
    failures: List[str] = []
    res["verdict"] = verdict
    res["tiers"] = tiers
    res["verdicts_lost"] = 0
    if verdict is not True:
        failures.append(f"ladder verdict was {verdict!r}, expected True")
    if tiers != ["xla", "native"]:
        failures.append(f"ladder hops were {tiers}, expected ['xla', 'native']")
    if pend.device != "native":
        failures.append(f"verdict served by {pend.device!r}, expected 'native'")
    text = metrics.reg.expose().decode()
    for sample in (
        'lodestar_bls_degrade_total{tier="xla",where="dispatch"} 1.0',
        'lodestar_bls_degrade_total{tier="native",where="dispatch"} 1.0',
    ):
        if sample not in text:
            failures.append(f"metric sample missing: {sample}")
    # the fused tier must come back for the NEXT verifier: the memo was
    # purged, and this instance keeps serving on XLA
    follow_up = v.verify_signature_sets_async(make_sets(2, start=8)).result()
    if follow_up is not True:
        failures.append("post-ladder dispatch (XLA tier) failed")
    summary = _validated_bundle(inspect_bundle, latest_bundle(out_dir), res)
    if summary is not None:
        ch = summary.get("chaos") or {}
        if (ch.get("last_fault") or {}).get("seam") != "bls.compile":
            failures.append("bundle chaos section missing the compile fault")
    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def scenario_cache_corrupt(seed: int, out_dir: str, inspect_bundle,
                           check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "cache_corrupt"}
    from lodestar_tpu.observatory.compile_ledger import CompileLedger

    seq0 = JOURNAL.seq
    failures: List[str] = []
    ledger_path = os.path.join(out_dir, "compile_ledger.json")
    ledger = CompileLedger().configure(path=ledger_path)
    with ledger.attribute("xla_split", bucket=4, device="cpu:0"):
        ledger.on_jax_event("/jax/core/compile/backend_compile_duration", 2.0)
    ledger.flush()
    if not os.path.exists(ledger_path):
        failures.append("ledger never persisted (scenario setup)")
    else:
        # flip bytes until the JSON actually breaks (a 16-byte flip all
        # landing in string payloads could, in principle, still parse) —
        # each round is still seed-deterministic
        for attempt in range(4):
            offsets = corrupt_file(ledger_path, seed=seed + attempt)
            try:
                json.load(open(ledger_path))
            except ValueError:
                break
        res["flipped_offsets"] = offsets[:8]
        # determinism: the same seed flips the same bytes
        probe = os.path.join(out_dir, "probe.bin")
        with open(probe, "wb") as f:
            f.write(b"A" * 256)
        first = corrupt_file(probe, seed=seed)
        with open(probe, "wb") as f:
            f.write(b"A" * 256)
        second = corrupt_file(probe, seed=seed)
        if first != second:
            failures.append("corrupt_file is not deterministic for a fixed seed")
        # survival: a fresh ledger over the corrupt file must come up
        # empty-but-alive, and must journal the corruption
        fresh = CompileLedger().configure(path=ledger_path)
        if fresh.to_dict():
            failures.append("corrupt ledger produced baseline records")
        events = _journal_since(seq0)
        if _first(events, lambda e: e.get("kind") == "cache.corrupt") is None:
            failures.append("no cache.corrupt journal event — corruption invisible")
    RECORDER.configure(forensics_dir=out_dir)
    bundle = RECORDER.dump("cache-corrupt", metric_reason="chaos")
    _validated_bundle(inspect_bundle, bundle, res)
    res["verdicts_lost"] = 0
    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def _tiny_compiled():
    """A real (tiny, ms-to-compile) CPU executable under the bls key
    schema — what the aot_corrupt scenario seeds its store with."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda *a: jnp.asarray(True))
    args = [jax.ShapeDtypeStruct((4,), jnp.float32)]
    return fn.lower(*args).compile()


class _TinyKernelVerifier:
    """Factory for a real TpuBlsVerifier whose kernels are tiny jits (a
    compile costs ms, not minutes) — the aot_corrupt scenario drives the
    REAL materialization ladder (store load -> corrupt -> recompile ->
    store save) through it with a live pool on top."""

    @staticmethod
    def build(aot_store):
        import jax.numpy as jnp

        from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier

        v = TpuBlsVerifier(buckets=(4,), fused=False, host_final_exp=False,
                           platform="cpu", aot_store=aot_store,
                           native_verifier=_StubNative())
        v._kernel = lambda key: (lambda *a: jnp.asarray(True))
        return v


def _aot_midwrite_child(plan_json: str, store_dir: str) -> None:
    """Spawn-child entry for the prewarmer-killed-mid-write class: arm
    the plan, then save an entry — the ``aot.midwrite`` seam SIGKILLs
    between the temp-file write and the rename, leaving an orphan temp,
    an un-updated manifest, and a stale writer lock behind."""
    sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from lodestar_tpu.aot.store import AotExecutableStore
    from lodestar_tpu.chaos import CHAOS as child_chaos
    from lodestar_tpu.chaos import FaultPlan as ChildPlan

    child_chaos.install(ChildPlan.from_json(plan_json))
    store = AotExecutableStore(path=store_dir)
    store.save("xla_full", 4, "midwrite-victim", _tiny_compiled())
    os._exit(7)  # plan did not fire: the parent treats this as a failure


def scenario_aot_corrupt(seed: int, out_dir: str, inspect_bundle,
                         check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "aot_corrupt", "verdicts_lost": 0}
    failures: List[str] = []
    from lodestar_tpu.aot.store import AotExecutableStore

    seq0 = JOURNAL.seq
    store_dir = os.path.join(out_dir, "aot_store")
    store = AotExecutableStore(path=store_dir)
    compiled = _tiny_compiled()

    # -- corrupt entry: checksum rejection + quarantine ----------------------
    key = store.save("xla_full", 4, "default", compiled)
    if key is None:
        failures.append("store save failed (scenario setup)")
    else:
        fpath = os.path.join(store_dir, store.keys()[key]["file"])
        res["flipped_offsets"] = corrupt_file(fpath, seed=seed)[:8]
        fresh = AotExecutableStore(path=store_dir)
        if fresh.load("xla_full", 4, "default") is not None:
            failures.append("corrupt store entry still loaded")
        if fresh.corrupt != 1:
            failures.append("corrupt entry not counted as corrupt")
        if not os.path.exists(fpath + ".quarantined"):
            failures.append("corrupt entry was not quarantined aside")

    # -- jax-version skew: eviction ------------------------------------------
    key2 = store.save("fused_full", 4, "default", compiled)
    if key2 is not None:
        mpath = os.path.join(store_dir, "manifest.json")
        doc = json.load(open(mpath))
        doc["entries"][key2]["jax"] = "0.0.0-skewed"
        json.dump(doc, open(mpath, "w"))
        skewed = AotExecutableStore(path=store_dir)
        if skewed.load("fused_full", 4, "default") is not None:
            failures.append("version-skewed entry still loaded")
        if skewed.skew != 1:
            failures.append("skewed entry not counted as skew")
        if key2 in skewed.keys():
            failures.append("skewed entry not evicted from the manifest")

    # -- truncated manifest: survivable + journaled --------------------------
    mpath = os.path.join(store_dir, "manifest.json")
    blob = open(mpath, "rb").read()
    open(mpath, "wb").write(blob[: max(1, len(blob) // 2)])
    truncated = AotExecutableStore(path=store_dir)
    if truncated.keys() != {}:
        failures.append("truncated manifest produced entries")

    # -- prewarmer killed mid-write (its own pristine store, so the
    # orphan/lock assertions are not confounded by the faults above) ---------
    kill_dir = os.path.join(out_dir, "aot_store_midwrite")
    plan = FaultPlan(seed).add("aot.midwrite", count=1)
    ctx = multiprocessing.get_context("spawn")
    p = ctx.Process(target=_aot_midwrite_child,
                    args=(plan.to_json(), kill_dir), daemon=True)
    p.start()
    p.join(60)
    if p.is_alive():
        p.kill()
        p.join(10)
        failures.append("midwrite child never died (plan did not fire)")
    elif p.exitcode != -9:
        failures.append(f"midwrite child exitcode {p.exitcode}, expected -9")
    entries_dir = os.path.join(kill_dir, "entries")
    orphans = [
        n for n in (os.listdir(entries_dir) if os.path.isdir(entries_dir) else [])
        if ".tmp" in n
    ]
    res["orphan_temp_files"] = len(orphans)
    if not orphans:
        failures.append("no orphan temp file from the killed writer")
    after_kill = AotExecutableStore(path=kill_dir)
    if after_kill.load("xla_full", 4, "midwrite-victim") is not None:
        failures.append("half-written entry was loadable")
    if after_kill.corrupt:
        failures.append("orphan temp misclassified as corruption (must be a plain miss)")
    # the dead child's writer lock must not wedge the next writer
    if after_kill.save("xla_split", 4, "default", compiled) is None:
        failures.append("stale writer lock from the killed child wedged the next save")

    # -- the node still verifies: live pool over the damaged store -----------
    from lodestar_tpu.chain.bls_pool import BlsBatchPool

    v = _TinyKernelVerifier.build(AotExecutableStore(path=store_dir))
    pool = BlsBatchPool(v, max_buffer_wait=0.002, flush_threshold=8,
                        pipeline_depth=2)
    RECORDER.configure(forensics_dir=out_dir, pool=pool, verifier=v)
    try:
        recovered = asyncio.run(run_jobs(pool, 4 if fast else 8))
    finally:
        pool.close()
        # the tiny always-True programs live in the PROCESS-global memo
        # under real bucket-4 keys — evict them or a later scenario's
        # real bucket-4 dispatch would inherit a forged-verdict stub
        from lodestar_tpu.crypto.bls.tpu_verifier import _PROGRAM_MEMO

        for ex in v._executors:
            for key in list(ex.compiled):
                _PROGRAM_MEMO.pop(v._memo_key(key, ex), None)
            ex.compiled.clear()
    res["verdicts_lost"] = recovered["verdicts_lost"]
    if recovered["verdicts_lost"]:
        failures.append(f"{recovered['verdicts_lost']} stranded futures")
    if recovered["outcomes"]["false"] or recovered["errors"]:
        failures.append(
            f"post-fault verdicts wrong: {recovered['outcomes']}, "
            f"{recovered['errors'][:2]}"
        )

    # -- evidence: journal events + a triagable bundle (the midwrite
    # kill's chaos.inject lives in the CHILD's journal and dies with it —
    # its evidence is the -9 exitcode + the orphan temp asserted above) ------
    events = _journal_since(seq0)
    for kind in ("aot.corrupt", "aot.skew"):
        if _first(events, lambda e, k=kind: e.get("kind") == k) is None:
            failures.append(f"no {kind} journal event — fault invisible")
    bundle = RECORDER.dump("aot-corrupt", metric_reason="chaos")
    summary = _validated_bundle(inspect_bundle, bundle, res)
    if summary is not None:
        aot = summary.get("aot") or {}
        if not aot.get("last_corrupt"):
            failures.append("bundle aot triage missing the corrupt event")
        if not aot.get("last_skew"):
            failures.append("bundle aot triage missing the skew event")
        if not aot.get("store"):
            failures.append("bundle aot triage missing the store path")
    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def _kill_child(plan_json: str, stage: str, base_dir: str) -> None:
    """Spawn-child entry for the bench-kill scenario: heartbeat once,
    then die the way a wedged bench stage does (SIGKILL from outside has
    the same observable shape as this in-process one)."""
    import os as _os

    _os.environ[  # the salvage scratch dir the parent will read back
        "BENCH_FORENSICS_DIR"
    ] = base_dir
    sys.path.insert(0, _REPO)
    from lodestar_tpu.chaos import CHAOS as child_chaos
    from lodestar_tpu.chaos import FaultPlan as ChildPlan
    from lodestar_tpu.forensics import salvage as child_salvage

    child_chaos.install(ChildPlan.from_json(plan_json))
    hb = child_salvage.Heartbeat(stage, interval_s=30.0)
    hb.beat()  # one synchronous snapshot so evidence exists before death
    child_chaos.maybe_kill("bench.kill", stage=stage)
    # plan didn't target us: exit clean (the parent treats that as a
    # scenario failure)
    hb.stop()


def scenario_bench_kill(seed: int, out_dir: str, inspect_bundle,
                        check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "bench_kill", "verdicts_lost": 0}
    failures: List[str] = []
    stage = "chaos_kill_stage"
    plan = FaultPlan(seed).add("bench.kill", count=1)
    ctx = multiprocessing.get_context("spawn")
    p = ctx.Process(
        target=_kill_child, args=(plan.to_json(), stage, out_dir), daemon=True
    )
    p.start()
    p.join(60)
    if p.is_alive():
        p.kill()
        p.join(10)
        failures.append("kill child never died (plan did not fire)")
    elif p.exitcode != -9:
        failures.append(f"child exitcode {p.exitcode}, expected -9 (SIGKILL)")
    prev = os.environ.get(salvage.BASE_DIR_ENV)
    os.environ[salvage.BASE_DIR_ENV] = out_dir
    try:
        bundle = salvage.latest_stage_bundle(stage, pid=p.pid)
    finally:
        if prev is None:
            os.environ.pop(salvage.BASE_DIR_ENV, None)
        else:
            os.environ[salvage.BASE_DIR_ENV] = prev
    if bundle is None:
        failures.append("no pid-scoped salvage bundle from the killed child")
    else:
        summary = _validated_bundle(inspect_bundle, bundle, res)
        if summary is not None:
            ch = summary.get("chaos") or {}
            if not ch.get("armed"):
                failures.append("salvage bundle missing the armed chaos plan")
    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


def scenario_forensics_io(seed: int, out_dir: str, inspect_bundle,
                          check_trace, fast: bool) -> Dict[str, Any]:
    res: Dict[str, Any] = {"name": "forensics_io", "verdicts_lost": 0}
    failures: List[str] = []
    RECORDER.configure(forensics_dir=out_dir)
    CHAOS.install(
        FaultPlan(seed).add("forensics.io", match={"section": "trace.json"},
                            count=1)
    )
    bundle = RECORDER.dump("chaos-io", metric_reason="chaos")
    CHAOS.disarm()
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    errs = manifest.get("errors") or {}
    if "trace.json" not in errs:
        failures.append(
            "injected section IO error not recorded in manifest.errors"
        )
    if "trace.json" in manifest.get("files", []):
        failures.append("failed section still listed as written")
    # partial evidence must still validate (per-section isolation)
    _validated_bundle(inspect_bundle, bundle, res)
    if failures:
        res.setdefault("failures", []).extend(failures)
    res["ok"] = not res.get("failures")
    return res


SCENARIOS = (
    scenario_device_loss,
    scenario_sharded_loss,
    scenario_device_wedge,
    scenario_compile_ladder,
    scenario_cache_corrupt,
    scenario_aot_corrupt,
    scenario_bench_kill,
    scenario_forensics_io,
)


def run_campaign(seed: int = 0, out_dir: Optional[str] = None,
                 fast: bool = False,
                 scenarios=SCENARIOS) -> Dict[str, Any]:
    """The whole campaign; returns the report dict (``ok`` is the gate)."""
    import tempfile

    inspect_bundle = load_tool("inspect_bundle")
    check_trace = load_tool("check_trace")
    if out_dir is None:
        out_dir = tempfile.mkdtemp(prefix="lodestar-chaos-")
    os.makedirs(out_dir, exist_ok=True)
    report: Dict[str, Any] = {
        "seed": seed, "out_dir": out_dir, "scenarios": {},
    }
    verdicts_lost = 0
    bundles: List[str] = []
    for fn in scenarios:
        scen_dir = os.path.join(out_dir, fn.__name__.replace("scenario_", ""))
        os.makedirs(scen_dir, exist_ok=True)
        try:
            out = fn(seed, scen_dir, inspect_bundle, check_trace, fast)
        except Exception as e:  # noqa: BLE001 — one broken scenario must not
            out = {                    # hide the others' results
                "name": fn.__name__, "ok": False,
                "failures": [f"scenario raised {type(e).__name__}: {e}"],
            }
        finally:
            CHAOS.disarm()
        report["scenarios"][out.get("name", fn.__name__)] = out
        verdicts_lost += int(out.get("verdicts_lost") or 0)
        bundles.extend(out.get("bundles") or [])
    loss = report["scenarios"].get("device_loss", {})
    report["verdicts_lost"] = verdicts_lost
    report["bundles_validated"] = len(bundles)
    report["time_to_quarantine_s"] = loss.get("time_to_quarantine_s")
    report["time_to_recover_s"] = loss.get("time_to_recover_s")
    report["throughput_recovery_ratio"] = loss.get("throughput_recovery_ratio")
    report["failures"] = {
        name: s["failures"]
        for name, s in report["scenarios"].items() if s.get("failures")
    }
    report["ok"] = verdicts_lost == 0 and all(
        s.get("ok") for s in report["scenarios"].values()
    )
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None,
                    help="bundle/trace scratch directory (default: mkdtemp)")
    ap.add_argument("--fast", action="store_true",
                    help="smaller job counts (tier-1 smoke size)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = run_campaign(seed=args.seed, out_dir=args.out_dir, fast=args.fast)
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        for name, s in report["scenarios"].items():
            mark = "ok " if s.get("ok") else "FAIL"
            print(f"{mark} {name}")
            for f in s.get("failures") or []:
                print(f"      {f}")
        print(
            f"verdicts_lost={report['verdicts_lost']} "
            f"bundles_validated={report['bundles_validated']} "
            f"time_to_quarantine_s={report['time_to_quarantine_s']} "
            f"time_to_recover_s={report['time_to_recover_s']} "
            f"recovery_ratio={report['throughput_recovery_ratio']}"
        )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
