"""Headline benchmark: BLS signature sets verified per second per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: a 128-set batch (MAX_SIGNATURE_SETS_PER_JOB in the reference,
packages/beacon-node/src/chain/bls/multithread/index.ts:39 — one worker-pool
job's worth, i.e. a full mainnet block's signature sets) through the round-4
SPLIT dispatch: the batched Miller-product kernel on device plus the native
C final exponentiation on the host (ops/batch_verify.miller_product_kernel
+ csrc/fastbls.c) — the production TpuBlsVerifier path, measured end-to-end
per dispatch (host packing excluded, reported separately).

Baseline (round-4, VERDICT r3 item 2): the native C batch verifier
(csrc/fastbls.c, portable 64-bit Montgomery code) measured on THIS host,
single core — the blst-class CPU path the reference runs behind its worker
pool.  BASELINE.md records that asm-grade blst is ~3-5x this portable-C
figure; the pure-Python oracle rate (the old, dishonest denominator) is
kept in extras for continuity.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

# Persistent XLA compilation cache: the wiring lives in the verifier now
# (round 6) so the NODE gets warm programs too; bench just points it at
# the repo-local cache (pre-warmed during the build round, gitignored).
from lodestar_tpu.crypto.bls.tpu_verifier import (  # noqa: E402
    configure_persistent_cache,
)

# env wins so the cold_start stage can point a grandchild at an EMPTY
# cache dir (the cold-variant measurement) without editing this file
configure_persistent_cache(
    os.environ.get("LODESTAR_TPU_JAX_CACHE") or os.path.join(_REPO, ".jax_cache")
)

# Stage-child salvage (round 9): pin the scratch dir in the environment
# BEFORE any child spawns so parent and children agree on where heartbeat
# bundles land — the parent reads the last one back on a stage timeout.
from lodestar_tpu.forensics import salvage  # noqa: E402

os.environ.setdefault(salvage.BASE_DIR_ENV, salvage.base_dir())

BATCH = int(os.environ.get("BENCH_BATCH", "128"))


class StageSkip(Exception):
    """A stage declining to run on THIS host (wrong backend, too few
    cores/devices, cold-compile budget exhaustion).  Distinct from a
    failure: the driver records the reason under
    ``extras.<stage>.skip_reason`` instead of an error string, so a
    published artifact says WHY a number is missing — a silent None and
    a crash repr both read as "something broke" three rounds later."""


# fn_name -> reason; filled by _stage in the parent when a child skips
_STAGE_SKIPS: dict = {}


def build_batch(n: int):
    from lodestar_tpu.ops.batch_verify import example_inputs

    return example_inputs(n)


def bench_lint():
    """Pre-flight invariant lint (tools/lint.py run_all): AST rules, the
    lock/race audit, the compile-cost audit of the test suite, and the
    jaxpr IR audit (including the limb-interval overflow proofs) of every
    fused entry point at the production bucket pair.

    Returns the violation dicts.  The gate RECORDS them in extras.lint
    instead of silently proceeding — a Mosaic-unsafe splice or an
    unlocked hot-path mutation must be visible in the bench artifact even
    on a run whose numbers look fine (BENCH_r05 was exactly a lint-class
    failure surfacing as rc=124).  Runs CPU-only in its own spawn child:
    tracing never needs the TPU, and the real device stages must not
    contend with it for the device lock."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from lodestar_tpu.analysis import run_all
    from lodestar_tpu.analysis.report import to_dicts

    return to_dicts(run_all(repo=_REPO))


def bench_pallas_fused(args, repeats: int = 3):
    """The round-5 production path: fused Pallas kernel dispatch, final
    exponentiation on device (ops/fused_verify.verify_signature_sets_fused)."""
    import jax

    from lodestar_tpu.ops.fused_verify import verify_signature_sets_fused

    if jax.default_backend() != "tpu":
        raise StageSkip(
            "Mosaic kernels need a TPU backend; interpret-mode rates are "
            "not comparable numbers"
        )
    fn = jax.jit(lambda *a: verify_signature_sets_fused(*a, interpret=False))
    out = fn(*args)
    assert bool(out), "benchmark batch failed to verify (pallas fused)"
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        assert bool(out)  # value read = hard sync
        times.append(time.perf_counter() - t0)
    dt = min(times)
    n = args[0].shape[0]
    return n / dt, dt


def bench_pallas_split(args, repeats: int = 3):
    """Fused Pallas Miller product on device + native C final exp on host."""
    import jax

    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.ops.fused_verify import miller_product_fused

    if jax.default_backend() != "tpu":
        raise StageSkip(
            "Mosaic kernels need a TPU backend; interpret-mode rates are "
            "not comparable numbers"
        )

    def kernel(*a):
        f, ok = miller_product_fused(*a, interpret=False)
        return f.a, ok

    fn = jax.jit(kernel)
    v = TpuBlsVerifier()
    f, ok = fn(*args)
    assert v._host_final_exp_verdict(f, ok), "benchmark batch failed (pallas split)"
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        f, ok = fn(*args)
        f.block_until_ready()
        verdict = v._host_final_exp_verdict(f, ok)
        times.append(time.perf_counter() - t0)
        assert verdict
    dt = min(times)
    n = args[0].shape[0]
    return n / dt, dt


def bench_split_dispatch(args, repeats: int = 3):
    """The split path: device Miller product + host C final exp, timed
    end-to-end (device compute + 2.4KB transfer + host tail)."""
    import jax

    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.ops.batch_verify import miller_product_kernel

    fn = jax.jit(miller_product_kernel)
    v = TpuBlsVerifier()  # host-final-exp helper (no packing here)
    f, ok = fn(*args)  # compile + warm
    assert v._host_final_exp_verdict(f, ok), "benchmark batch failed to verify"
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        f, ok = fn(*args)
        f.block_until_ready()
        verdict = v._host_final_exp_verdict(f, ok)
        times.append(time.perf_counter() - t0)
        assert verdict
    dt = min(times)
    n = args[0].shape[0]
    return n / dt, dt


def bench_fused_dispatch(args, repeats: int = 3):
    """The single fused device program (final exp on device)."""
    import jax

    from lodestar_tpu.ops.batch_verify import verify_signature_sets_kernel

    fn = jax.jit(verify_signature_sets_kernel)
    out = fn(*args)
    assert bool(out), "benchmark batch failed to verify"
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        assert bool(out)  # value read = hard sync
        times.append(time.perf_counter() - t0)
    dt = min(times)
    n = args[0].shape[0]
    return n / dt, dt


def bench_cpu_native(n: int = 128):
    """Native C batch verify, single core — the honest vs_baseline
    denominator.  Returns None when the C toolchain is unavailable."""
    import secrets

    from lodestar_tpu.crypto.bls import curve as C
    from lodestar_tpu.crypto.bls.api import interop_secret_key
    from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from lodestar_tpu.native import fastbls

    if not fastbls.have_native():
        return None
    packed = []
    for i in range(n):
        sk = interop_secret_key(i % 16)
        msg = bytes([i]) * 32
        packed.append(
            (
                [C.g1_to_bytes(C.G1_GEN * sk.value)],
                msg,
                C.g2_to_bytes(hash_to_g2(msg) * sk.value),
            )
        )
    coeffs = [secrets.randbits(64) | 1 for _ in packed]
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        ok = fastbls.batch_verify(packed, coeffs)
        dt = time.perf_counter() - t0
        assert ok
        best = dt if best is None else min(best, dt)
    return n / best


def bench_cpu_oracle(n: int = 2):
    """Pure-Python bigint oracle rate (extras only — continuity with the
    r1-r3 denominator)."""
    from lodestar_tpu.crypto.bls.api import (
        interop_secret_key,
        verify_multiple_signatures,
    )

    sets = []
    for i in range(n):
        sk = interop_secret_key(i)
        msg = bytes([i]) * 32
        sets.append((sk.to_public_key(), msg, sk.sign(msg)))
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        ok = verify_multiple_signatures(sets)
        dt = time.perf_counter() - t0
        assert ok
        best = dt if best is None else min(best, dt)
    return n / best


def bench_limb_mul(buckets=(4, 128), iters: int = 20):
    """fp_mul microbench, ladder vs MXU (PR 18): ns per field multiply at
    the gossip (4) and headline (128) bucket widths for each limb-mul
    mode, plus the measured ladder->mxu ratio published as
    ``fp_mul_speedup_mxu`` (run-ledger tripwired, direction +1).

    Operands are tower-shaped ``(bucket, 54, 50)`` strict digit stacks so
    the timed contraction is the batched MXU shape the pairing actually
    runs (the 54-lane flat tower axis becomes the MXU batch dimension),
    not a single-row toy.  Each mode is its own jit program (mode is a
    static argname), warmed before timing.
    """
    import numpy as np

    import jax

    from lodestar_tpu.ops import limbs as fl

    lanes = 54
    rng = np.random.default_rng(0x18)
    out = {"unit": "ns/fp_mul", "modes": {}}
    ladder_ns = {}
    mxu_ns = {}
    for mode in ("ladder", "mxu"):
        per_bucket = {}
        for b in buckets:
            a = rng.integers(0, 256, size=(b, lanes, fl.NLIMBS)).astype(np.float32)
            c = rng.integers(0, 256, size=(b, lanes, fl.NLIMBS)).astype(np.float32)
            aj = jax.numpy.asarray(a)
            cj = jax.numpy.asarray(c)
            fl.fp_mul(aj, cj, mode=mode).block_until_ready()  # compile
            best = None
            for _ in range(iters):
                t0 = time.perf_counter()
                fl.fp_mul(aj, cj, mode=mode).block_until_ready()
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            ns = best / (b * lanes) * 1e9
            per_bucket[str(b)] = round(ns, 1)
            (ladder_ns if mode == "ladder" else mxu_ns)[b] = ns
        out["modes"][mode] = per_bucket
    head = max(buckets)
    out["fp_mul_speedup_mxu"] = round(ladder_ns[head] / mxu_ns[head], 3)
    out["fp_mul_speedup_mxu_small"] = round(
        ladder_ns[min(buckets)] / mxu_ns[min(buckets)], 3
    )
    return out


def bench_small_bucket(n: int = 16, budget_s: float = 120.0):
    """Dispatch latency for the small gossip bucket (VERDICT r3 weak 10:
    the latency distribution the node actually feels).  Skips (with the
    reason recorded) when the program is not already in the compile
    cache."""
    import jax

    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.ops.fused_verify import miller_product_fused

    if jax.default_backend() != "tpu":
        raise StageSkip(
            "Mosaic kernels need a TPU backend; interpret-mode rates are "
            "not comparable numbers"
        )
    args = build_batch(n)

    def kernel(*a):
        f, ok = miller_product_fused(*a, interpret=False)
        return f.a, ok

    fn = jax.jit(kernel)
    v = TpuBlsVerifier()
    t0 = time.perf_counter()
    f, ok = fn(*args)
    f.block_until_ready()
    if time.perf_counter() - t0 > budget_s:
        raise StageSkip(  # don't risk the driver's wall clock
            f"cold compile ate the {budget_s:.0f}s budget "
            "(bucket-16 program not in the persistent cache)"
        )
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        f, ok = fn(*args)
        f.block_until_ready()
        v._host_final_exp_verdict(f, ok)
        times.append(time.perf_counter() - t0)
    return min(times)


def bench_scale_250k(budget_s: float = 180.0):
    """Mainnet-preset 250k-validator measurements (BASELINE.md configs
    #3/#5 groundwork; reference perf state: state-transition/test/perf/
    util.ts:49): steady-state epoch transition (warm HTR cache + reused
    EpochContext — a following node's condition) and a 128-attestation
    block apply.  Returns dict or None over budget."""
    import time as _t

    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.params import MAINNET
    from lodestar_tpu.spec_test_util.perf_state import build_perf_state
    from lodestar_tpu.ssz import Fields
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.misc import compute_start_slot_at_epoch
    from lodestar_tpu.state_transition.upgrade import state_types

    t_start = _t.perf_counter()
    cfg = ChainConfig(
        PRESET_BASE="mainnet", MIN_GENESIS_TIME=0,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16384,
    )
    state, ctx = build_perf_state(MAINNET, cfg, 250_000)
    state_types(MAINNET, state).BeaconState.hash_tree_root(state)  # warm subtrees
    if _t.perf_counter() - t_start > budget_s:
        return None

    # block apply at a non-boundary slot with a full load of attestations
    from lodestar_tpu.state_transition.block import process_attestation

    epoch = state.slot // MAINNET.SLOTS_PER_EPOCH
    att_slot = state.slot - MAINNET.MIN_ATTESTATION_INCLUSION_DELAY
    boundary = bytes(
        state.block_roots[
            compute_start_slot_at_epoch(MAINNET, epoch) % MAINNET.SLOTS_PER_HISTORICAL_ROOT
        ]
    )
    atts = []
    for index in range(min(MAINNET.MAX_ATTESTATIONS, ctx.get_committee_count_per_slot(epoch))):
        committee = ctx.get_beacon_committee(att_slot, index)
        atts.append(
            Fields(
                aggregation_bits=[True] * len(committee),
                data=Fields(
                    slot=att_slot, index=index,
                    beacon_block_root=bytes(
                        state.block_roots[att_slot % MAINNET.SLOTS_PER_HISTORICAL_ROOT]
                    ),
                    source=Fields(
                        epoch=state.current_justified_checkpoint.epoch,
                        root=bytes(state.current_justified_checkpoint.root),
                    ),
                    target=Fields(epoch=epoch, root=boundary),
                ),
                signature=b"\x00" * 96,
            )
        )
    t0 = _t.perf_counter()
    for att in atts:
        process_attestation(MAINNET, ctx, state, att, False)
    block_atts_ms = (_t.perf_counter() - t0) * 1e3

    # steady-state epoch transition: reused ctx, warm HTR cache
    t0 = _t.perf_counter()
    process_slots(MAINNET, cfg, state, state.slot + 1, ctx)
    epoch_ms = (_t.perf_counter() - t0) * 1e3
    return {
        "epoch_transition_ms_250k": round(epoch_ms),
        "block_attestations_ms_250k": round(block_atts_ms),
        "n_attestations": len(atts),
    }


def bench_dev_chain(time_budget_s: float = 150.0):
    """blocks/s through DevChain.run with the DEVICE verifier — the e2e
    figure (STF + fork choice + batched kernel per block).  Soft-skipped
    when the kernel for the bucket is not already in the compile cache."""
    import asyncio
    import time as _t

    from lodestar_tpu.chain.bls_pool import BlsBatchPool
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.node.dev_chain import DevChain
    from lodestar_tpu.params import MINIMAL

    cfg = ChainConfig(
        PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
        ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
    )

    from lodestar_tpu.observatory import DeviceSampler

    async def run():
        # bucket 128 = the exact program shape the headline measurement
        # just compiled/cached — the extra never waits on a fresh compile
        verifier = TpuBlsVerifier(buckets=(128,))
        pool = BlsBatchPool(verifier, max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, cfg, 16, pool)
        # device telemetry alongside the e2e run: HBM + busy-ratio rows,
        # and the sampler's SELF-MEASURED overhead published in extras
        # (the <1% bound is a measurement, not a promise)
        sampler = DeviceSampler(interval_s=0.25, window=240).start()
        t0 = _t.perf_counter()
        await dev.advance_slot(1)  # includes any compile
        if _t.perf_counter() - t0 > time_budget_s:
            sampler.stop()
            pool.close()
            return None
        n = 8
        t1 = _t.perf_counter()
        for slot in range(2, 2 + n):
            await dev.advance_slot(slot)
        rate = n / (_t.perf_counter() - t1)
        sampler.stop()
        pool.close()
        return {
            "rate": rate,
            "stage_seconds": {k: round(v, 4) for k, v in verifier.stage_seconds.items()},
            "inflight_peak": pool.inflight_peak,
            "sampler_overhead_ratio": sampler.overhead_ratio(),
            "sampler_ticks": sampler.ticks,
            "telemetry": sampler.snapshot()["devices"],
            "trace_path": _dump_stage_trace("dev_chain"),
        }

    _enable_stage_trace()
    # timeouts soft-skip (budget guard); other errors propagate so the
    # caller's retry can fire on transient tunnel flakes
    try:
        return asyncio.run(asyncio.wait_for(run(), time_budget_s * 2))
    except asyncio.TimeoutError:
        return None


def bench_range_sync(time_budget_s: float = 240.0):
    """blocks/s replaying a multi-epoch dev-chain segment through
    process_chain_segment on a FRESH chain — the range-sync throughput of
    BASELINE.md configs #4/#5 (reference: sync/range/chain.ts:85 feeding
    1000+ signature sets per batch to the worker pool).  Cross-block
    batching means the whole segment verifies in a handful of dispatches."""
    import asyncio
    import time as _t

    from lodestar_tpu.chain.bls_pool import BlsBatchPool
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.node.dev_chain import DevChain
    from lodestar_tpu.params import MINIMAL

    cfg = ChainConfig(
        PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
        ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
    )

    async def run():
        t_start = _t.perf_counter()
        verifier = TpuBlsVerifier(buckets=(128,))
        pool = BlsBatchPool(verifier, max_buffer_wait=0.005)
        # build a 2-epoch segment on a producer chain
        producer = DevChain(MINIMAL, cfg, 16, pool)
        segment = []
        nslots = 2 * MINIMAL.SLOTS_PER_EPOCH
        for slot in range(1, 1 + nslots):
            root = await producer.advance_slot(slot)
            segment.append(producer.chain.get_block_by_root(root))
            if _t.perf_counter() - t_start > time_budget_s:
                pool.close()
                return None
        # replay through a fresh chain (same genesis) via the segment path
        consumer = DevChain(MINIMAL, cfg, 16, pool)
        _enable_stage_trace()  # trace the replay only, not segment build
        t0 = _t.perf_counter()
        n = await consumer.chain.process_chain_segment(segment)
        dt = _t.perf_counter() - t0
        pool.close()
        assert n == len(segment), f"only {n}/{len(segment)} imported"
        return {
            "rate": n / dt,
            "stage_seconds": {k: round(v, 4) for k, v in verifier.stage_seconds.items()},
            "inflight_peak": pool.inflight_peak,
            "trace_path": _dump_stage_trace("range_sync"),
        }

    try:
        return asyncio.run(asyncio.wait_for(run(), time_budget_s * 2))
    except asyncio.TimeoutError:
        return None


def bench_multichip(time_budget_s: float = 540.0):
    """Throughput scaling of the round-8 executor pool: whole merged
    batches placed least-loaded/round-robin across N device executors vs
    the same workload on 1 device (SURVEY §2.10 ICI data-parallel, rebuilt
    as batch-level scheduling).  Publishes the north-star
    ``sets_per_sec_per_chip`` plus ``scaling_efficiency`` =
    rate(N)/(N * rate(1)).  Skips (reason recorded in
    ``extras.multichip.skip_reason``) on single-core hosts, with < 2
    devices, or when the per-device warmup would blow the stage budget."""
    import time as _t

    # fail FAST, before jax init: on a single-core host the 8 forced
    # virtual devices all time-share one core, the per-device warmup
    # compiles never finish inside the 600s stage bound, and the driver
    # burns the full timeout killing a wedged child (the PR 18 rc=124)
    if (os.cpu_count() or 1) < 2:
        raise StageSkip(
            "single-core host: forced virtual devices oversubscribe one "
            "core and the per-device warmup blows the stage budget"
        )

    import jax

    from lodestar_tpu import tracing
    from lodestar_tpu.crypto.bls.api import interop_secret_key
    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.crypto.bls.verifier import SingleSignatureSet

    devices = jax.devices()
    if len(devices) < 2:
        raise StageSkip(f"{len(devices)} JAX device(s): scaling needs >= 2")
    backend = jax.default_backend()
    # CPU virtual devices share the host's cores — bucket 4 keeps the smoke
    # test affordable; real TPUs measure the production block-sized bucket
    bucket = 128 if backend == "tpu" else 4
    default_n = len(devices) if backend == "tpu" else min(4, len(devices))
    n_dev = min(len(devices), int(os.environ.get("BENCH_MULTICHIP_DEVICES", default_n)))
    n_batches = 2 * n_dev

    def make_bench_sets(k):
        out = []
        for i in range(k):
            sk = interop_secret_key(i % 8)  # repeated pubkeys: cache-hit shape
            msg = bytes([i % 256, i // 256]) * 16
            out.append(
                SingleSignatureSet(
                    pubkey=sk.to_public_key(), signing_root=msg,
                    signature=sk.sign(msg).to_bytes(),
                )
            )
        return out

    sets = make_bench_sets(bucket)

    def throughput(verifier, s=None, warmups=None):
        s = sets if s is None else s
        packed = verifier.pack(s)
        assert packed is not None
        # warm every executor (compile/cache-load excluded from the rate)
        n_warm = verifier.n_devices if warmups is None else warmups
        warm = [verifier.dispatch(packed) for _ in range(n_warm)]
        ok = all(p.result() for p in warm)
        assert ok, "multichip warmup batch failed to verify"
        t0 = _t.perf_counter()
        pending = [verifier.dispatch(packed) for _ in range(n_batches)]
        assert all(p.result() for p in pending)
        dt = _t.perf_counter() - t0
        return n_batches * len(s) / dt

    # tracing on for BOTH runs so the span overhead cancels out of
    # scaling_efficiency (single-run spans carry device="default")
    _enable_stage_trace()
    t_start = _t.perf_counter()
    single = TpuBlsVerifier(buckets=(bucket,))
    rate1 = throughput(single)
    if _t.perf_counter() - t_start > time_budget_s:
        raise StageSkip(  # don't risk the driver's wall clock
            f"cold compile ate the {time_budget_s:.0f}s budget before the "
            "multi-device run"
        )
    multi = TpuBlsVerifier(buckets=(bucket,), devices=devices[:n_dev])
    rate_n = throughput(multi)
    placed = {
        (s.args or {}).get("device")
        for s in tracing.TRACER.spans()
        if s.name == "bls.dispatch"
    } - {None, "default"}  # "default" = the single-device control run

    # --- sharded part (round 11): ONE mesh-spanning shard_map program ----
    # carries the whole merged batch — the whole-mesh headline the sharded
    # tier is judged on, vs n_dev * the single-chip rate at the SAME
    # bucket.  On TPU both buckets are the production 128; CPU virtual
    # devices share the host's cores, so the mesh batch keeps a local-2
    # shard (bucket = 2 * n_dev) to stay inside the stage budget.
    sharded = None
    # a COLD mesh compile can eat minutes: only attempt the part with at
    # least half the stage budget left (prewarm/.jax_cache make it a
    # ~30s load on a warmed box; the skip is visible as sharded: null)
    if _t.perf_counter() - t_start < time_budget_s * 0.5:
        shard_bucket = 128 if backend == "tpu" else 2 * n_dev
        try:
            sh_sets = sets if shard_bucket == bucket else make_bench_sets(shard_bucket)
            if shard_bucket == bucket:
                rate1s = rate1
            else:
                single_s = TpuBlsVerifier(buckets=(shard_bucket,))
                rate1s = throughput(single_s, sh_sets)
            mesh_v = TpuBlsVerifier(
                buckets=(shard_bucket,), devices=devices[:n_dev],
                sharded=True, sharded_min_batch=shard_bucket,
            )
            rate_sh = throughput(mesh_v, sh_sets, warmups=2)
            # the 2 warmups also ride the mesh, so EVERY measured batch
            # must have too — a mid-measurement sticky degrade otherwise
            # blends pool-tier dispatches into the sharded headline
            assert (
                mesh_v.sharded_fallbacks == 0
                and mesh_v.sharded_batches >= n_batches + 2
            ), (
                f"sharded tier did not carry the measurement: "
                f"{mesh_v.sharded_batches} mesh batches for "
                f"{n_batches} + 2 dispatches "
                f"(fallbacks={mesh_v.sharded_fallbacks})"
            )
            sharded = {
                "bucket": shard_bucket,
                "mesh_devices": n_dev,
                # the new whole-mesh headline (run_ledger tripwire -10%)
                "bls_sig_sets_per_s": round(rate_sh, 2),
                "sets_per_sec_1chip": round(rate1s, 2),
                "scaling_efficiency": round(rate_sh / (n_dev * rate1s), 3),
                "sharded_batches": mesh_v.sharded_batches,
                "combine": mesh_v.sharded_combine,
            }
            # mesh observatory (ISSUE 20): attribute the measured
            # 1 - scaling_efficiency gap over the span timeline the
            # stage already records — communication from span-attributed
            # collective time (0 without device events, i.e. CPU),
            # serial-host from the mesh batches' queue/pack/final_exp,
            # shard imbalance absorbing the remainder (no per-shard
            # walls here), so the components reconcile with the gap by
            # construction and run_ledger can trend each term
            from lodestar_tpu.observatory import attribution as _attr

            report = _attr.attribute_spans(tracing.TRACER.spans())
            mesh_b = [b for b in report["batches"] if b["sharded"]]
            wall_s = sum(b["e2e_s"] for b in mesh_b) or (
                n_batches * shard_bucket / rate_sh
            )
            sharded["scaling_loss"] = _attr.scaling_loss_breakdown(
                efficiency=rate_sh / (n_dev * rate1s),
                wall_s=wall_s,
                comm_s=sum(
                    b["stages"]["collective_combine"] for b in mesh_b
                ),
                serial_host_s=sum(
                    b["stages"]["queue"] + b["stages"]["pack"]
                    + b["stages"]["final_exp"]
                    for b in mesh_b
                ),
            )
            sharded["mesh_overlap_ratio"] = report["overlap_ratio"]
            if mesh_b:
                sharded["pipeline_bubble_ms"] = round(
                    sum(b["stages"]["pipeline_bubble"] for b in mesh_b)
                    / len(mesh_b) * 1e3, 3,
                )
        except Exception as e:  # noqa: BLE001 — the stage publishes regardless
            sharded = {"error": str(e)[:300]}

    return {
        "n_devices": n_dev,
        "bucket": bucket,
        "sets_per_sec_1chip": round(rate1, 2),
        "sets_per_sec_total": round(rate_n, 2),
        # the whole-mesh headline (ISSUE 7 satellite 2): roadmap item 1's
        # sharded kernel is judged on THIS number, so it exists first
        "bls_sig_sets_per_s": round(rate_n, 2),
        "sets_per_sec_per_chip": round(rate_n / n_dev, 2),
        "scaling_efficiency": round(rate_n / (n_dev * rate1), 3),
        "devices_used": len(placed),
        "sharded": sharded,
        "trace_path": _dump_stage_trace("multichip"),
    }


def bench_cold_start_probe():
    """Grandchild entry for the cold_start stage: process start -> first
    verified batch, in THIS process (spawned fresh, so the figure covers
    interpreter boot + jax import + trace/compile/cache-load + dispatch
    + readback — the number ROADMAP item 4's AOT-serialization work will
    be judged against).  The compile ledger rides along so the stage can
    say WHAT the startup paid (cold compile vs warm cache load)."""
    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.observatory import COMPILE_LEDGER, process_age_s

    verifier = TpuBlsVerifier(buckets=(BATCH,))
    pending = verifier.dispatch(build_batch(BATCH))
    ok = pending.result()
    age = process_age_s()
    assert ok, "cold-start probe batch failed to verify"
    return {
        "first_verified_batch_s": round(age, 2),
        "batch": BATCH,
        # session-only view: what THIS startup paid — the on-disk ledger
        # baseline (every historical run's events) must not ride along
        "ledger": COMPILE_LEDGER.session_summary(),
        "cache_dir": os.environ.get("LODESTAR_TPU_JAX_CACHE"),
    }


def bench_cold_start_aot_probe():
    """Grandchild entry for the cold_start ``aot`` variant: process start
    -> first verified batch with a POPULATED durable AOT store and a
    load-only warmup — the rolling-restart number ROADMAP item 4's <10 s
    target is judged on.  The persistent .jax_cache env points at an
    EMPTY scratch dir so the figure can only come from the store (a
    load-only warmup never compiles; a store miss here surfaces as an
    error, not a silent recompile)."""
    from lodestar_tpu.aot import AOT_STORE
    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.observatory import COMPILE_LEDGER, process_age_s

    bucket = int(os.environ.get("BENCH_AOT_BUCKET", "4"))
    verifier = TpuBlsVerifier(buckets=(bucket,), load_only=True)
    warmup_s = verifier.warmup(load_only=True)
    pending = verifier.dispatch(build_batch(bucket))
    ok = pending.result()
    age = process_age_s()
    assert ok, "aot cold-start probe batch failed to verify"
    return {
        "first_verified_batch_s": round(age, 2),
        "bucket": bucket,
        "warmup_s": round(warmup_s, 2),
        "native_tier_only": verifier._native_tier_only,
        "aot_store": AOT_STORE.stats() if AOT_STORE.enabled else None,
        # session-only view: what THIS startup paid (the aot_load rows
        # are the whole point — zero cold/warm_load must appear)
        "ledger": COMPILE_LEDGER.session_summary(),
        "store": os.environ.get("LODESTAR_TPU_AOT_STORE"),
    }


def bench_cold_start(time_budget_s: float = 600.0):
    """Cold-start stage (ISSUE 7 + ISSUE 9): process start -> first
    verified batch, measured in fresh spawn grandchildren.

    Three variants: **warm** (the repo-local persistent cache, trace +
    lower + warm backend load per program), **aot** (a durable AOT
    executable store populated by tools/prewarm.py + an EMPTY persistent
    cache — the rolling-restart case, load-only warmup, ROADMAP item 4's
    <10 s target; CPU boxes proxy with bucket 4) and **cold** (an empty
    cache dir — the first-boot-on-new-topology worst case; skipped when
    the remaining budget cannot absorb a full compile, or when
    BENCH_COLD_VARIANT=0; BENCH_AOT_VARIANT=0 skips the aot variant).
    The numbers feed perf_report's ``cold_start_warm_s`` /
    ``cold_start_aot_s`` / ``cold_start_cold_s`` tripwires (+25%)."""
    import shutil
    import subprocess
    import tempfile

    t0 = time.perf_counter()

    def probe(cache_dir, fn_name="bench_cold_start_probe", extra_env=None):
        # the warm/cold variants measure the PERSISTENT-CACHE tiers: an
        # ambient LODESTAR_TPU_AOT_STORE (production env, conftest) would
        # silently serve them aot_loads — and poison their tripwire
        # baselines — so the store env is cleared unless the variant
        # explicitly pins it (the aot probe does)
        env = {"LODESTAR_TPU_AOT_STORE": "", **(extra_env or {})}
        env_before = {
            k: os.environ.get(k)
            for k in ({"LODESTAR_TPU_JAX_CACHE"} | set(env))
        }
        os.environ["LODESTAR_TPU_JAX_CACHE"] = cache_dir
        for k, v in env.items():
            os.environ[k] = v
        try:
            ctx = multiprocessing.get_context("spawn")
            q = ctx.Queue()
            p = ctx.Process(
                target=_stage_child, args=(q, fn_name, ()),
                daemon=True,
            )
            p.start()
            remaining = max(30.0, time_budget_s - (time.perf_counter() - t0))
            try:
                status, payload = q.get(timeout=remaining)
            except Exception:  # queue.Empty
                p.terminate()
                p.join(10)
                if p.is_alive():
                    p.kill()
                    p.join(10)
                return {"error": f"timeout after {remaining:.0f}s"}
            p.join(30)
            return payload if status == "ok" else {"error": payload}
        finally:
            for k, v in env_before.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    out = {"warm": probe(os.path.join(_REPO, ".jax_cache"))}
    out["warm_s"] = (out["warm"] or {}).get("first_verified_batch_s")

    # -- aot variant: prewarm a scratch store (riding the warm repo
    # cache), then restart against it with an empty persistent cache ----
    remaining = time_budget_s - (time.perf_counter() - t0)
    if os.environ.get("BENCH_AOT_VARIANT", "1") in ("0", "false", "no"):
        out["aot"] = {"skipped": "BENCH_AOT_VARIANT=0"}
    elif remaining < 90.0:
        out["aot"] = {"skipped": f"budget exhausted ({remaining:.0f}s left)"}
    else:
        bucket = os.environ.get("BENCH_AOT_BUCKET", "4")
        aot_scratch = tempfile.mkdtemp(prefix="coldstart-aot-store-")
        empty_cache = tempfile.mkdtemp(prefix="coldstart-aot-jax-cache-")
        try:
            pw = subprocess.run(
                [sys.executable, os.path.join(_REPO, "tools", "prewarm.py"),
                 "--store", aot_scratch, "--buckets", bucket,
                 "--devices", "1", "--json"],
                capture_output=True, text=True,
                timeout=max(60.0, remaining - 60.0),
                env={**os.environ,
                     "LODESTAR_TPU_JAX_CACHE": os.path.join(_REPO, ".jax_cache")},
            )
            if pw.returncode != 0:
                out["aot"] = {
                    "error": f"prewarm rc={pw.returncode}: {pw.stderr[-300:]}"
                }
            else:
                out["aot"] = probe(
                    empty_cache, fn_name="bench_cold_start_aot_probe",
                    extra_env={"LODESTAR_TPU_AOT_STORE": aot_scratch,
                               "BENCH_AOT_BUCKET": bucket},
                )
                out["aot_s"] = (out["aot"] or {}).get("first_verified_batch_s")
                try:
                    out["aot"]["prewarm"] = json.loads(pw.stdout)["stats"]
                except (ValueError, KeyError, TypeError):
                    pass
        except subprocess.TimeoutExpired:
            out["aot"] = {"error": "prewarm timeout"}
        finally:
            shutil.rmtree(aot_scratch, ignore_errors=True)
            shutil.rmtree(empty_cache, ignore_errors=True)

    remaining = time_budget_s - (time.perf_counter() - t0)
    if os.environ.get("BENCH_COLD_VARIANT", "1") in ("0", "false", "no"):
        out["cold"] = {"skipped": "BENCH_COLD_VARIANT=0"}
    elif remaining < 120.0:
        # the documented budget guard: a cold variant that cannot absorb
        # a full compile would just burn the remaining wall on a doomed
        # grandchild and report a timeout error instead of a clean skip
        out["cold"] = {"skipped": f"budget exhausted ({remaining:.0f}s left)"}
    else:
        scratch = tempfile.mkdtemp(prefix="coldstart-jax-cache-")
        try:
            out["cold"] = probe(scratch)
            out["cold_s"] = (out["cold"] or {}).get("first_verified_batch_s")
        finally:
            shutil.rmtree(scratch, ignore_errors=True)
    return out


def bench_firehose(time_budget_s: float = 300.0):
    """Sustained-load stage (ISSUE 6): drive tools/firehose.run_firehose
    against a REAL BlsBatchPool on the deterministic stub verifier (zero
    XLA work — the pool's scheduling, shedding, and backpressure are the
    system under test, not the kernel) and publish:

    - ``sustained_sets_per_s_at_slo``: the highest offered rate on a
      x1.5 ladder whose p99 queue-wait stays under the SLO with zero
      drops — the number a capacity planner needs;
    - an induced overload run at 2x that rate: bounded queue memory,
      zero stranded futures, block-proposal-lane p99, every drop
      accounted in the dropped_total{reason,lane} analog, and the
      shed-rate-triggered "overload" diagnostic bundle validated by
      tools/inspect_bundle.py.

    The stage rides the PR 5 salvage path like every other stage (a
    wedged run leaves heartbeat bundles) and runs the forensics watchdog
    so a stall inside the window produces its own bundle."""
    import asyncio
    import tempfile

    from lodestar_tpu import tracing
    from lodestar_tpu.chain.bls_pool import BlsBatchPool
    from lodestar_tpu.forensics.bundle import latest_bundle
    from lodestar_tpu.forensics.recorder import RECORDER
    from tools.firehose import StubVerifier, run_firehose
    from tools.inspect_bundle import summarize as bundle_summarize
    from tools.inspect_bundle import validate as bundle_validate

    slo_ms = float(os.environ.get("BENCH_FIREHOSE_SLO_MS", 100.0))
    window_s = float(os.environ.get("BENCH_FIREHOSE_WINDOW_S", 3.0))
    t_start = time.perf_counter()

    def fresh_pool(**kw):
        tracing.TRACER.clear()
        tracing.enable(65536)
        # overload bundles default OFF: ladder rungs that shed must not
        # dump through the (not yet configured) global recorder — only
        # the induced-overload run below opts in, after RECORDER.configure
        kw.setdefault("overload_shed_threshold", 0)
        return BlsBatchPool(StubVerifier(), max_buffer_wait=0.01,
                            flush_threshold=128, pipeline_depth=2, **kw)

    def run(pool, **kw):
        async def _go():
            try:
                return await run_firehose(pool, **kw)
            finally:
                pool.close()

        return asyncio.run(_go())

    # -- SLO ladder: find the sustained rate ---------------------------------
    rate, sustained = 1000.0, None
    while time.perf_counter() - t_start < time_budget_s * 0.6:
        report = run(fresh_pool(), rate=rate, duration_s=window_s,
                     deadline_ms=1000.0)
        ok = (
            report["stranded_futures"] == 0
            and report["dropped_sets_total"] == 0
            and report["intake_shed_total"] == 0
            and (report["queue_wait"]["p99_ms"] or 0) <= slo_ms
            and report["achieved_sets_per_s"] >= 0.9 * rate
        )
        if not ok:
            break
        sustained = report
        rate *= 1.5
    if sustained is None:
        return {"error": "no rate met the SLO", "slo_p99_queue_wait_ms": slo_ms}
    sustained_rate = sustained["offered_rate_sets_per_s"]

    # -- induced overload: offered = 2x sustained ----------------------------
    forensics_dir = tempfile.mkdtemp(prefix="firehose-forensics-")
    pool = fresh_pool(max_queue_length=4096,
                      overload_shed_threshold=128, overload_cooldown_s=5.0)
    RECORDER.configure(forensics_dir=forensics_dir, pool=pool)
    RECORDER.start_watchdog(deadline_s=20.0)
    try:
        overload = run(pool, rate=2.0 * sustained_rate,
                       duration_s=window_s * 2, deadline_ms=400.0)
    finally:
        RECORDER.stop_watchdog()
    bundle = latest_bundle(forensics_dir)
    bundle_errors = bundle_valid = bundle_overload = None
    if bundle:
        errs = bundle_validate(bundle)
        bundle_valid = not errs
        bundle_errors = errs or None
        bundle_overload = bundle_summarize(bundle).get("overload")

    def slim(r):
        return {
            k: r[k] for k in (
                "offered_rate_sets_per_s", "achieved_sets_per_s",
                "bls_sig_sets_per_s",
                "queue_wait", "e2e", "block_lane_p99_ms", "dropped_sets",
                "intake_shed_total", "unaccounted_sets", "stranded_futures",
                "pending_sets_after", "outcomes",
            ) if k in r
        }

    return {
        "slo_p99_queue_wait_ms": slo_ms,
        "window_s": window_s,
        "sustained_sets_per_s_at_slo": sustained_rate,
        "sustained": slim(sustained),
        "overload": slim(overload),
        "overload_bundle": bundle,
        "overload_bundle_valid": bundle_valid,
        "overload_bundle_errors": bundle_errors,
        "overload_bundle_summary": bundle_overload,
    }


def _enable_stage_trace() -> None:
    """Span-trace the e2e stages (ISSUE 2): each emits a Chrome-trace
    artifact whose path rides in the stage's extras."""
    from lodestar_tpu import tracing

    tracing.TRACER.clear()
    tracing.enable(16384)


def _dump_stage_trace(stage: str):
    import tempfile

    from lodestar_tpu import tracing

    out_dir = os.environ.get("BENCH_TRACE_DIR", tempfile.gettempdir())
    path = os.path.join(out_dir, f"lodestar_tpu_trace_{stage}.json")
    try:
        return tracing.write_chrome_trace(tracing.TRACER, path)
    except OSError:
        return None


def bench_wedge(seconds: float = 3600.0):
    """Fault-injection stage (tests only): wedge until the parent's
    timeout kills us — the BENCH_r05 failure shape on demand.  The
    heartbeat must leave a salvageable bundle behind."""
    time.sleep(seconds)


def bench_chaos(time_budget_s: float = 240.0):
    """Chaos campaign stage (docs/chaos.md): every fault class against a
    live stub pool — device loss/wedge, the fused→XLA→native compile
    ladder, cache corruption, a SIGKILLed grandchild, bundle-IO faults —
    publishing the ROADMAP item-5 guarantee numbers: zero undiagnosable
    deaths (every bundle inspect_bundle-valid), ``verdicts_lost`` (must
    be 0), ``time_to_quarantine_s`` / ``time_to_recover_s``, and the
    post-fault throughput recovery ratio.  Stub device programs only —
    no XLA work, no device contention with the throughput stages.

    Runs the campaign CLI in a fresh grandchild: this stage child has
    already imported jax WITHOUT the forced virtual-device flag (the
    module-level cache configure), and the stub executor pool needs the
    8 virtual CPU devices — which must be set before jax ever imports."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_campaign.py"),
         "--seed", os.environ.get("BENCH_CHAOS_SEED", "0"), "--json"],
        capture_output=True, text=True, env=env,
        timeout=max(30.0, time_budget_s - 30.0),
    )
    try:
        # the report is the final JSON object on stdout (check_trace's
        # per-file OK lines precede it)
        report = json.loads(proc.stdout[proc.stdout.index("{"):])
    except ValueError:
        raise RuntimeError(
            f"chaos campaign produced no report (rc={proc.returncode}): "
            f"{proc.stderr[-500:]}"
        )
    return {
        "ok": report["ok"],
        "seed": report["seed"],
        "verdicts_lost": report["verdicts_lost"],
        "bundles_validated": report["bundles_validated"],
        "time_to_quarantine_s": report["time_to_quarantine_s"],
        "time_to_recover_s": report["time_to_recover_s"],
        "throughput_recovery_ratio": report["throughput_recovery_ratio"],
        "scenarios": {
            name: s.get("ok") for name, s in report["scenarios"].items()
        },
        "failures": report["failures"] or None,
    }


def _stage_child(q, fn_name, args):
    """Subprocess entry: run one benchmark stage and ship the result (or
    the error repr) back over the queue.  A salvage heartbeat snapshots
    this child's journal/trace/in-flight state to the scratch dir so a
    timeout kill still leaves evidence (the rc=124 fix)."""
    try:
        hb = salvage.start_heartbeat(fn_name)
    except Exception:  # scratch-disk trouble must not fail the stage
        hb = None
    try:
        # chaos activation seam: an armed LODESTAR_TPU_CHAOS_PLAN env var
        # injects faults into ANY bench stage (docs/chaos.md); a no-op
        # (one env read) when unset
        from lodestar_tpu.chaos import install_from_env

        install_from_env()
    except Exception:
        pass
    try:
        fn = globals()[fn_name]
        q.put(("ok", fn(*args)))
    except StageSkip as e:
        q.put(("skip", str(e)))
    except BaseException as e:  # noqa: BLE001 - includes SystemExit from jax
        try:
            q.put(("err", f"{type(e).__name__}: {e}"))
        except Exception:  # unpicklable payloads must not hang the parent
            q.put(("err", type(e).__name__))
    finally:
        if hb is not None:
            hb.stop()


def _stage(fn_name, args=(), timeout_s=600.0, retries=1):
    """Run one benchmark stage in a spawn subprocess with a hard
    wall-clock bound (round-6 graceful degradation): a Mosaic compile
    failure, an axon tunnel hang, or a runaway compile in ONE stage must
    not rc=124 the whole run — the stage reports null + the error string
    in extras and the gate still publishes every other number.  Transient
    tunnel errors get one retry; a wrong verdict (AssertionError in the
    stage) comes back as an error string and is NOT retried."""
    timeout_s = float(os.environ.get("BENCH_STAGE_TIMEOUT_S", timeout_s))
    last_err = None
    for attempt in range(retries + 1):
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        # daemon=True is only a die-with-parent guarantee (timeouts are
        # handled by the explicit terminate/kill below) — but a daemonic
        # child may not have children of its own, and the cold_start
        # stage measures fresh spawn grandchildren, so it alone runs
        # non-daemonic
        p = ctx.Process(
            target=_stage_child, args=(q, fn_name, args),
            daemon=(fn_name != "bench_cold_start"),
        )
        p.start()
        try:
            status, payload = q.get(timeout=timeout_s)
        except Exception:  # queue.Empty
            p.terminate()
            p.join(10)
            if p.is_alive():
                # a wedged JAX runtime can swallow SIGTERM while holding
                # the TPU device lock — SIGKILL or every later stage fails
                # device init ("Device or resource busy")
                p.kill()
                p.join(10)
            # salvage: attach THIS child's last heartbeat bundle (pid-
            # scoped — a child killed before its first beat must not be
            # blamed on a previous run's leftovers) so the timeout is a
            # diagnosable artifact, not just a wall-clock number
            last_err = {
                "error": f"timeout after {timeout_s:.0f}s",
                "bundle": salvage.latest_stage_bundle(fn_name, pid=p.pid),
            }
            print(f"{fn_name}: {last_err['error']}", file=sys.stderr)
            continue
        p.join(30)
        if status == "ok":
            return payload, None
        if status == "skip":
            _STAGE_SKIPS[fn_name] = payload
            print(f"{fn_name}: skipped — {payload}", file=sys.stderr)
            return None, None
        last_err = payload
        print(f"{fn_name} attempt {attempt}: {payload}", file=sys.stderr)
        if payload.startswith("AssertionError"):
            break  # miscompile-class failure: report, don't retry
    return None, last_err


def main() -> None:
    errors = {}
    # pre-flight lint: violations ride extras.lint (never a dead gate —
    # a broken invariant should show up NEXT TO the numbers it taints)
    lint_violations, lint_err = _stage("bench_lint", (), 420)
    if lint_err:
        errors["lint"] = lint_err
    args = build_batch(BATCH)
    modes = []

    def run_mode(name, fn_name, timeout_s):
        out, err = _stage(fn_name, (args,), timeout_s)
        if err:
            errors[name] = err
        rate, dt = out if out else (None, None)
        modes.append((name, rate, dt))
        return rate, dt

    # round-6: the fused Pallas dispatch is the headline CANDIDATE, but the
    # split path is ALWAYS measured and published — a fused Mosaic failure
    # (BENCH_r05 rc=124) degrades to a reported error, never a dead gate.
    pf_rate, pf_dt = run_mode("pallas-fused", "bench_pallas_fused", 600)
    ps_rate, ps_dt = run_mode("pallas-split+host-final-exp", "bench_pallas_split", 600)
    split_rate, split_dt = run_mode("xla-split+host-final-exp", "bench_split_dispatch", 900)
    fused_dt = None
    if pf_rate is None and ps_rate is None and split_rate is None:
        _fused_rate, fused_dt = run_mode("xla-fused", "bench_fused_dispatch", 900)
    live = [(m, r, d) for m, r, d in modes if r is not None]
    if not live:
        raise RuntimeError(f"all dispatch modes failed: {errors}")
    mode, dev_rate, dt = max(live, key=lambda t: t[1])
    cpu_native = bench_cpu_native()
    cpu_oracle = bench_cpu_oracle()
    small_dt, err = _stage("bench_small_bucket", (), 300)
    if err:
        errors["bucket16"] = err
    # PR-18 MXU limb multiply: ladder vs MXU fp_mul microbench — the
    # per-multiply number under the headline, published with its own
    # run-ledger tripwire (fp_mul_speedup_mxu)
    limb_mul, err = _stage("bench_limb_mul", (), 420)
    if err:
        errors["limb_mul"] = err
    chain_res, err = _stage("bench_dev_chain", (), 420)
    if err:
        errors["dev_chain"] = err
    chain_res = chain_res or {}
    chain_rate = chain_res.get("rate")
    range_res, err = _stage("bench_range_sync", (), 600)
    if err:
        errors["range_sync"] = err
    range_res = range_res or {}
    range_rate = range_res.get("rate")
    # multichip scaling: CPU hosts need forced virtual devices; the flag is
    # scoped to this one stage's subprocess (spawn children inherit env)
    had_flags = "XLA_FLAGS" in os.environ
    prev_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in prev_flags:
        os.environ["XLA_FLAGS"] = (
            prev_flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    multichip, err = _stage("bench_multichip", (), 600)
    if had_flags:
        os.environ["XLA_FLAGS"] = prev_flags
    else:
        os.environ.pop("XLA_FLAGS", None)
    if err:
        errors["multichip"] = err

    # structured skips: a stage that declined (StageSkip) publishes WHY
    # under its own extras entry — extras.<stage>.skip_reason
    def _skip_extra(fn_name):
        reason = _STAGE_SKIPS.get(fn_name)
        return {"skip_reason": reason} if reason else None

    multichip = multichip or _skip_extra("bench_multichip")
    scale, err = _stage("bench_scale_250k", (), 420)
    if err:
        errors["scale_250k"] = err
    # sustained-load survival (ISSUE 6): SLO-bounded sustained rate plus an
    # induced-overload run with full drop accounting and a validated
    # overload bundle — stub verifier, so no device contention here
    firehose, err = _stage("bench_firehose", (), 420)
    if err:
        errors["firehose"] = err
    # chaos campaign (ISSUE 8): zero undiagnosable deaths under injected
    # faults + self-healing pool recovery numbers — stub programs only,
    # so it contends with nothing
    chaos, err = _stage("bench_chaos", (), 300)
    if err:
        errors["chaos"] = err
    # cold start (ISSUE 7): process start -> first verified batch, warm
    # (repo cache) and cold (empty cache) variants in fresh grandchildren —
    # the ROADMAP item 4 baseline.  Runs LAST among device stages so its
    # cold grandchild never contends with the throughput measurements.
    cold_start, err = _stage("bench_cold_start", (), 900)
    if err:
        errors["cold_start"] = err
    cold_start = cold_start or {}
    import jax

    baseline = cpu_native if cpu_native else cpu_oracle
    # run-ledger pre-flight (ISSUE 7): this run's headline numbers vs the
    # most recent committed run that produced each — the delta that used
    # to require hand-reading two JSON files, now IN the artifact
    try:
        from lodestar_tpu.observatory import run_ledger

        perf_deltas = run_ledger.deltas_vs_previous(_REPO, backend=jax.default_backend(), current={
            "bls_sig_sets_per_s_per_chip": dev_rate,
            "bls_sig_sets_per_s": (multichip or {}).get("bls_sig_sets_per_s"),
            "scaling_efficiency": (multichip or {}).get("scaling_efficiency"),
            "bls_sig_sets_per_s_sharded": (
                (multichip or {}).get("sharded") or {}
            ).get("bls_sig_sets_per_s"),
            "scaling_efficiency_sharded": (
                (multichip or {}).get("sharded") or {}
            ).get("scaling_efficiency"),
            "dev_chain_blocks_per_s": chain_rate,
            "range_sync_blocks_per_s": range_rate,
            "cold_start_warm_s": cold_start.get("warm_s"),
            "cold_start_aot_s": cold_start.get("aot_s"),
            "cold_start_cold_s": cold_start.get("cold_s"),
            "dispatch_ms": dt * 1e3 if dt else None,
            "epoch_transition_ms_250k": (scale or {}).get("epoch_transition_ms_250k"),
            "sustained_sets_per_s_at_slo": (firehose or {}).get(
                "sustained_sets_per_s_at_slo"
            ),
            "fp_mul_speedup_mxu": (limb_mul or {}).get("fp_mul_speedup_mxu"),
        })
    except Exception as e:  # noqa: BLE001 - the gate publishes regardless
        perf_deltas = {"error": str(e)}
    print(
        json.dumps(
            {
                "metric": "bls_sig_sets_per_s_per_chip",
                "value": round(dev_rate, 2),
                "unit": "sig-sets/s",
                "vs_baseline": round(dev_rate / baseline, 2),
                "extras": {
                    "batch": BATCH,
                    "dispatch_ms": round(dt * 1e3, 2),
                    "dispatch_mode": mode,
                    "dispatch_ms_pallas_fused": round(pf_dt * 1e3, 2) if pf_dt else None,
                    "dispatch_ms_pallas_split": round(ps_dt * 1e3, 2) if ps_dt else None,
                    "dispatch_ms_split": round(split_dt * 1e3, 2) if split_dt else None,
                    "dispatch_ms_fused": round(fused_dt * 1e3, 2) if fused_dt else None,
                    "sets_per_s_split": round(split_rate, 2) if split_rate else None,
                    "dispatch_ms_bucket16": round(small_dt * 1e3, 2) if small_dt else None,
                    "pallas_fused": _skip_extra("bench_pallas_fused"),
                    "pallas_split": _skip_extra("bench_pallas_split"),
                    "bucket16": _skip_extra("bench_small_bucket"),
                    "cpu_native_sets_per_s": round(cpu_native, 1) if cpu_native else None,
                    "cpu_oracle_sets_per_s": round(cpu_oracle, 3),
                    "baseline_kind": "fastbls-c" if cpu_native else "python-oracle",
                    "dev_chain_blocks_per_s": round(chain_rate, 3) if chain_rate else None,
                    "dev_chain_stage_seconds": chain_res.get("stage_seconds"),
                    "dev_chain_inflight_peak": chain_res.get("inflight_peak"),
                    "dev_chain_trace": chain_res.get("trace_path"),
                    "range_sync_blocks_per_s": round(range_rate, 3) if range_rate else None,
                    "range_sync_stage_seconds": range_res.get("stage_seconds"),
                    "range_sync_inflight_peak": range_res.get("inflight_peak"),
                    "range_sync_trace": range_res.get("trace_path"),
                    "dev_chain_sampler_overhead_ratio": chain_res.get(
                        "sampler_overhead_ratio"
                    ),
                    "limb_mul": limb_mul,
                    "multichip": multichip,
                    "scale_250k": scale,
                    "firehose": firehose,
                    "chaos": chaos,
                    "cold_start": cold_start or None,
                    "perf_deltas": perf_deltas,
                    "lint": {
                        "violations": lint_violations,
                        "count": len(lint_violations) if lint_violations is not None else None,
                    },
                    # where stage children heartbeat their salvage bundles
                    # (a timed-out stage's last-known state lives here)
                    "forensics_dir": os.environ.get(salvage.BASE_DIR_ENV),
                    "stage_errors": errors or None,
                    "backend": jax.default_backend(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
