"""Headline benchmark: BLS signature sets verified per second per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: a 128-set batch (MAX_SIGNATURE_SETS_PER_JOB in the reference,
packages/beacon-node/src/chain/bls/multithread/index.ts:39 — one worker-pool
job's worth, i.e. a full mainnet block's signature sets) through the batched
device kernel, measured end-to-end per dispatch (device compute; host
packing excluded, reported in extras).

Baseline: the measured host-CPU batch-verify path on this machine — the
pure-Python bigint oracle's verify_multiple_signatures (the reference's
blst-native C path is not runnable in this image; BASELINE.md records the
caveat).  vs_baseline = device rate / measured CPU rate.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Persistent XLA compilation cache: the batched-verify program costs
# minutes of TPU compile cold; the repo-local cache (pre-warmed during the
# build round, gitignored) brings a driver re-run down to seconds.
_REPO = os.path.dirname(os.path.abspath(__file__))
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

BATCH = 128


def build_batch(n: int):
    from lodestar_tpu.ops.batch_verify import example_inputs

    return example_inputs(n)


def bench_device(args, repeats: int = 3):
    import jax

    from lodestar_tpu.ops.batch_verify import verify_signature_sets_kernel

    fn = jax.jit(verify_signature_sets_kernel)
    out = fn(*args)  # compile + warm
    assert bool(out), "benchmark batch failed to verify"
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        r = fn(*args)
        r.block_until_ready()
        times.append(time.perf_counter() - t0)
    dt = min(times)
    return BATCH / dt, dt


def bench_cpu_oracle(n: int = 2):
    """Oracle (pure python bigint) batch verify throughput per set.

    n=2 keeps the baseline measurement to a couple of bigint pairings
    (~seconds) — the per-set rate extrapolates linearly and the driver's
    wall-clock budget belongs to the device measurement."""
    from lodestar_tpu.crypto.bls.api import (
        interop_secret_key,
        verify_multiple_signatures,
    )

    sets = []
    for i in range(n):
        sk = interop_secret_key(i)
        msg = bytes([i]) * 32
        sets.append((sk.to_public_key(), msg, sk.sign(msg)))
    best = None
    for _ in range(3):  # best-of-3: a single 2-set run is timing-noisy
        t0 = time.perf_counter()
        ok = verify_multiple_signatures(sets)
        dt = time.perf_counter() - t0
        assert ok
        best = dt if best is None else min(best, dt)
    return n / best


def bench_dev_chain(time_budget_s: float = 150.0):
    """blocks/s through DevChain.run with the DEVICE verifier — the e2e
    figure (STF + fork choice + batched kernel per block).  Soft-skipped
    when the kernel for the small bucket is not already in the compile
    cache (first dispatch over budget) so the driver's wall clock is never
    at risk."""
    import asyncio
    import time as _t

    from lodestar_tpu.chain.bls_pool import BlsBatchPool
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.crypto.bls.tpu_verifier import TpuBlsVerifier
    from lodestar_tpu.node.dev_chain import DevChain
    from lodestar_tpu.params import MINIMAL

    cfg = ChainConfig(
        PRESET_BASE="minimal", MIN_GENESIS_TIME=0, SHARD_COMMITTEE_PERIOD=0,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16,
        ALTAIR_FORK_EPOCH=2**64 - 1, BELLATRIX_FORK_EPOCH=2**64 - 1,
    )

    async def run():
        # bucket 128 = the exact program shape the headline measurement
        # just compiled/cached — the extra never waits on a fresh compile
        verifier = TpuBlsVerifier(buckets=(128,))
        pool = BlsBatchPool(verifier, max_buffer_wait=0.005)
        dev = DevChain(MINIMAL, cfg, 16, pool)
        t0 = _t.perf_counter()
        await dev.advance_slot(1)  # includes any compile
        if _t.perf_counter() - t0 > time_budget_s:
            pool.close()
            return None
        n = 8
        t1 = _t.perf_counter()
        for slot in range(2, 2 + n):
            await dev.advance_slot(slot)
        rate = n / (_t.perf_counter() - t1)
        pool.close()
        return rate

    try:
        return asyncio.run(asyncio.wait_for(run(), time_budget_s * 2))
    except Exception:
        return None


def main() -> None:
    args = build_batch(BATCH)
    dev_rate, dt = bench_device(args)
    cpu_rate = bench_cpu_oracle()
    chain_rate = bench_dev_chain()
    import jax

    print(
        json.dumps(
            {
                "metric": "bls_sig_sets_per_s_per_chip",
                "value": round(dev_rate, 2),
                "unit": "sig-sets/s",
                "vs_baseline": round(dev_rate / cpu_rate, 2),
                "extras": {
                    "batch": BATCH,
                    "dispatch_ms": round(dt * 1e3, 2),
                    "cpu_baseline_sets_per_s": round(cpu_rate, 3),
                    "dev_chain_blocks_per_s": round(chain_rate, 3) if chain_rate else None,
                    "backend": jax.default_backend(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
