"""Headline benchmark: BLS signature sets verified per second per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

North star (BASELINE.md): verify all signatures of a full mainnet block
(~128 sets) against a ~500k-validator state in <50 ms on one host — >=10x
the reference's blst CPU path. ``vs_baseline`` is measured speedup of the
TPU batch-verify dispatch over the same workload on this host's CPU
single-set path (the stand-in for the blst-native worker pool baseline,
reference: packages/beacon-node/src/chain/bls/multithread/index.ts).

Round 1: the JAX BLS core is under construction; until the pairing kernel
lands this prints a sha256-throughput placeholder line (clearly labeled as
such in the metric name) with vs_baseline=1.0 so the driver has a stable
JSON schema to record.
"""

from __future__ import annotations

import json
import time


def bench_placeholder() -> dict:
    import hashlib

    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < 0.5:
        hashlib.sha256(b"x" * 1024).digest()
        n += 1
    elapsed = time.perf_counter() - t0
    return {
        "metric": "placeholder_sha256_ops_per_s",
        "value": round(n / elapsed, 2),
        "unit": "ops/s",
        "vs_baseline": 1.0,
    }


def main() -> None:
    print(json.dumps(bench_placeholder()))


if __name__ == "__main__":
    main()
