/* fastbls: native BLS12-381 batch signature verification.
 *
 * The CPU-side counterpart of the TPU kernels (lodestar_tpu/ops/):
 *  - the honest CPU baseline for bench.py (blst-class role: the reference's
 *    native dep @chainsafe/blst, SURVEY.md section 2.9 - supranational C/asm;
 *    this is portable C with 64-bit Montgomery limbs, no asm),
 *  - the host-side final exponentiation for the split TPU dispatch (the
 *    batched Miller product is batch-parallel work the device keeps; the
 *    single-element final exp is serial work the host does faster),
 *  - a fast CPU fallback verifier behind the IBlsVerifier boundary.
 *
 * All algorithms mirror the Python bigint oracle (crypto/bls/) which is
 * itself differential-tested against RFC 9380 vectors and the device
 * kernels.  Constants are generated (tools/gen_fastbls_consts.py), never
 * transcribed.
 *
 * Representation: Fq = 6 x uint64 little-endian limbs, Montgomery form
 * (R = 2^384).  Towers: Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3-(u+1)),
 * Fq12 = Fq6[w]/(w^2-v).  Miller loop uses the same inversion-free
 * jacobian line formulas as ops/pairing.py (lines scaled by Fq2 subfield
 * factors, killed by the easy part of the final exponentiation); the hard
 * part uses the BLS12 x-chain computing f^(3*lambda) - is-one verdicts and
 * pairing-equality checks are unaffected by the cube (gcd(3, r) = 1).
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#include "fastbls_consts.h"

typedef struct { uint64_t d[6]; } fp_t;
typedef struct { fp_t c0, c1; } fp2_t;
typedef struct { fp2_t c0, c1, c2; } fp6_t;
typedef struct { fp6_t c0, c1; } fp12_t;
typedef struct { fp_t x, y, z; } g1_t;   /* jacobian; z==0 => infinity */
typedef struct { fp2_t x, y, z; } g2_t;  /* jacobian; z==0 => infinity */

/* ---------------------------------------------------------------- fp --- */

static const fp_t FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static inline void fp_copy(fp_t *r, const fp_t *a) { *r = *a; }

static inline int fp_is_zero(const fp_t *a) {
    uint64_t acc = 0;
    for (int i = 0; i < 6; i++) acc |= a->d[i];
    return acc == 0;
}

static inline int fp_equal(const fp_t *a, const fp_t *b) {
    uint64_t acc = 0;
    for (int i = 0; i < 6; i++) acc |= a->d[i] ^ b->d[i];
    return acc == 0;
}

/* r = a - p if a >= p */
static inline void fp_reduce_once(fp_t *a) {
    uint64_t t[6];
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        unsigned __int128 diff = (unsigned __int128)a->d[i] - FB_P[i] - (uint64_t)borrow;
        t[i] = (uint64_t)diff;
        borrow = (diff >> 64) & 1; /* 1 if borrowed */
    }
    if (!borrow)
        for (int i = 0; i < 6; i++) a->d[i] = t[i];
}

static inline void fp_add(fp_t *r, const fp_t *a, const fp_t *b) {
    unsigned __int128 carry = 0;
    for (int i = 0; i < 6; i++) {
        carry += (unsigned __int128)a->d[i] + b->d[i];
        r->d[i] = (uint64_t)carry;
        carry >>= 64;
    }
    fp_reduce_once(r);
}

static inline void fp_sub(fp_t *r, const fp_t *a, const fp_t *b) {
    unsigned __int128 borrow = 0;
    uint64_t t[6];
    for (int i = 0; i < 6; i++) {
        unsigned __int128 diff = (unsigned __int128)a->d[i] - b->d[i] - (uint64_t)borrow;
        t[i] = (uint64_t)diff;
        borrow = (diff >> 64) & 1;
    }
    if (borrow) { /* add p back */
        unsigned __int128 carry = 0;
        for (int i = 0; i < 6; i++) {
            carry += (unsigned __int128)t[i] + FB_P[i];
            t[i] = (uint64_t)carry;
            carry >>= 64;
        }
    }
    for (int i = 0; i < 6; i++) r->d[i] = t[i];
}

static inline void fp_neg(fp_t *r, const fp_t *a) {
    if (fp_is_zero(a)) { *r = FP_ZERO; return; }
    fp_t p; memcpy(p.d, FB_P, sizeof p.d);
    fp_sub(r, &p, a);
}

static inline void fp_dbl(fp_t *r, const fp_t *a) { fp_add(r, a, a); }

/* CIOS Montgomery multiplication. */
static void fp_mul(fp_t *r, const fp_t *a, const fp_t *b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 6; i++) {
        unsigned __int128 carry = 0;
        uint64_t ai = a->d[i];
        for (int j = 0; j < 6; j++) {
            carry += (unsigned __int128)ai * b->d[j] + t[j];
            t[j] = (uint64_t)carry;
            carry >>= 64;
        }
        carry += t[6];
        t[6] = (uint64_t)carry;
        t[7] = (uint64_t)(carry >> 64);

        uint64_t m = t[0] * FB_PINV;
        carry = (unsigned __int128)m * FB_P[0] + t[0];
        carry >>= 64;
        for (int j = 1; j < 6; j++) {
            carry += (unsigned __int128)m * FB_P[j] + t[j];
            t[j - 1] = (uint64_t)carry;
            carry >>= 64;
        }
        carry += t[6];
        t[5] = (uint64_t)carry;
        t[6] = t[7] + (uint64_t)(carry >> 64);
        t[7] = 0;
    }
    for (int i = 0; i < 6; i++) r->d[i] = t[i];
    /* t may still be >= p (but < 2p given p < 2^383) */
    fp_reduce_once(r);
}

static inline void fp_sqr(fp_t *r, const fp_t *a) { fp_mul(r, a, a); }

/* MSB-first square-and-multiply; e given as 6 LE limbs. */
static void fp_pow(fp_t *r, const fp_t *a, const uint64_t e[6]) {
    fp_t result, base = *a;
    memcpy(result.d, FB_R1, sizeof result.d); /* mont(1) */
    int top = 5;
    while (top >= 0 && e[top] == 0) top--;
    if (top < 0) { *r = result; return; }
    int bit = 63;
    while (!((e[top] >> bit) & 1)) bit--;
    for (int i = top; i >= 0; i--) {
        for (int j = (i == top ? bit : 63); j >= 0; j--) {
            fp_sqr(&result, &result);
            if ((e[i] >> j) & 1) fp_mul(&result, &result, &base);
        }
    }
    *r = result;
}

static void fp_inv(fp_t *r, const fp_t *a) { fp_pow(r, a, FB_P_MINUS_2); }

/* sqrt for p % 4 == 3: a^((p+1)/4); returns 1 on success. */
static int fp_sqrt(fp_t *r, const fp_t *a) {
    fp_t root, chk;
    fp_pow(&root, a, FB_P_PLUS_1_DIV_4);
    fp_sqr(&chk, &root);
    if (!fp_equal(&chk, a)) return 0;
    *r = root;
    return 1;
}

static void fp_from_mont(fp_t *r, const fp_t *a) {
    /* multiply by 1 (non-mont): one Montgomery reduction */
    fp_t one = FP_ZERO;
    one.d[0] = 1;
    fp_mul(r, a, &one);
}

static void fp_to_mont(fp_t *r, const fp_t *a) {
    fp_t r2; memcpy(r2.d, FB_R2, sizeof r2.d);
    fp_mul(r, a, &r2);
}

/* big-endian 48-byte I/O (values in [0, p)); returns 0 if out of range */
static int fp_from_bytes(fp_t *r, const uint8_t *in) {
    fp_t v;
    for (int i = 0; i < 6; i++) {
        uint64_t limb = 0;
        for (int j = 0; j < 8; j++) limb = (limb << 8) | in[(5 - i) * 8 + j];
        v.d[i] = limb;
    }
    /* range check v < p */
    int lt = 0;
    for (int i = 5; i >= 0; i--) {
        if (v.d[i] < FB_P[i]) { lt = 1; break; }
        if (v.d[i] > FB_P[i]) { lt = 0; break; }
    }
    if (!lt) return 0;
    fp_to_mont(r, &v);
    return 1;
}

static void fp_to_bytes(uint8_t *out, const fp_t *a) {
    fp_t v;
    fp_from_mont(&v, a);
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            out[(5 - i) * 8 + j] = (uint8_t)(v.d[i] >> (8 * (7 - j)));
}

/* lexicographic "greater than (p-1)/2" on the non-mont value */
static int fp_is_lex_greater(const fp_t *a) {
    fp_t v;
    fp_from_mont(&v, a);
    for (int i = 5; i >= 0; i--) {
        if (v.d[i] > FB_P_MINUS_1_DIV_2[i]) return 1;
        if (v.d[i] < FB_P_MINUS_1_DIV_2[i]) return 0;
    }
    return 1; /* equal: not greater, but (p-1)/2 is not attainable by y of a curve point pair midpoint; treat as not greater */
}

static int fp_is_odd(const fp_t *a) {
    fp_t v;
    fp_from_mont(&v, a);
    return (int)(v.d[0] & 1);
}

/* ---------------------------------------------------------------- fp2 -- */

static const fp2_t *FP2_P_FROB_V = (const fp2_t *)FB_FROB_V;
static const fp2_t *FP2_P_FROB_V2 = (const fp2_t *)FB_FROB_V2;
static const fp2_t *FP2_P_FROB_W = (const fp2_t *)FB_FROB_W;

static inline void fp2_zero(fp2_t *r) { r->c0 = FP_ZERO; r->c1 = FP_ZERO; }
static inline void fp2_one(fp2_t *r) {
    memcpy(r->c0.d, FB_R1, sizeof r->c0.d);
    r->c1 = FP_ZERO;
}
static inline int fp2_is_zero(const fp2_t *a) { return fp_is_zero(&a->c0) && fp_is_zero(&a->c1); }
static inline int fp2_equal(const fp2_t *a, const fp2_t *b) {
    return fp_equal(&a->c0, &b->c0) && fp_equal(&a->c1, &b->c1);
}
static inline void fp2_add(fp2_t *r, const fp2_t *a, const fp2_t *b) {
    fp_add(&r->c0, &a->c0, &b->c0);
    fp_add(&r->c1, &a->c1, &b->c1);
}
static inline void fp2_sub(fp2_t *r, const fp2_t *a, const fp2_t *b) {
    fp_sub(&r->c0, &a->c0, &b->c0);
    fp_sub(&r->c1, &a->c1, &b->c1);
}
static inline void fp2_neg(fp2_t *r, const fp2_t *a) {
    fp_neg(&r->c0, &a->c0);
    fp_neg(&r->c1, &a->c1);
}
static inline void fp2_dbl(fp2_t *r, const fp2_t *a) { fp2_add(r, a, a); }
static inline void fp2_conj(fp2_t *r, const fp2_t *a) {
    r->c0 = a->c0;
    fp_neg(&r->c1, &a->c1);
}

/* Karatsuba: 3 fp muls */
static void fp2_mul(fp2_t *r, const fp2_t *a, const fp2_t *b) {
    fp_t t0, t1, s0, s1, m;
    fp_mul(&t0, &a->c0, &b->c0);
    fp_mul(&t1, &a->c1, &b->c1);
    fp_add(&s0, &a->c0, &a->c1);
    fp_add(&s1, &b->c0, &b->c1);
    fp_mul(&m, &s0, &s1);
    fp_sub(&m, &m, &t0);
    fp_sub(&m, &m, &t1);
    fp_sub(&r->c0, &t0, &t1);
    r->c1 = m;
}

static void fp2_sqr(fp2_t *r, const fp2_t *a) {
    /* (a0+a1)(a0-a1) + 2 a0 a1 u */
    fp_t s, d, m;
    fp_add(&s, &a->c0, &a->c1);
    fp_sub(&d, &a->c0, &a->c1);
    fp_mul(&m, &a->c0, &a->c1);
    fp_mul(&r->c0, &s, &d);
    fp_dbl(&r->c1, &m);
}

static void fp2_mul_fp(fp2_t *r, const fp2_t *a, const fp_t *k) {
    fp_mul(&r->c0, &a->c0, k);
    fp_mul(&r->c1, &a->c1, k);
}

static void fp2_inv(fp2_t *r, const fp2_t *a) {
    fp_t n0, n1, norm, ninv;
    fp_sqr(&n0, &a->c0);
    fp_sqr(&n1, &a->c1);
    fp_add(&norm, &n0, &n1);
    fp_inv(&ninv, &norm);
    fp_mul(&r->c0, &a->c0, &ninv);
    fp_t t;
    fp_mul(&t, &a->c1, &ninv);
    fp_neg(&r->c1, &t);
}

/* xi = 1 + u multiplication (Fq6 nonresidue) */
static void fp2_mul_xi(fp2_t *r, const fp2_t *a) {
    fp_t t0, t1;
    fp_sub(&t0, &a->c0, &a->c1);
    fp_add(&t1, &a->c0, &a->c1);
    r->c0 = t0;
    r->c1 = t1;
}

static void fp2_pow(fp2_t *r, const fp2_t *a, const uint64_t e[6]) {
    fp2_t result, base = *a;
    fp2_one(&result);
    int top = 5;
    while (top >= 0 && e[top] == 0) top--;
    if (top < 0) { *r = result; return; }
    int bit = 63;
    while (!((e[top] >> bit) & 1)) bit--;
    for (int i = top; i >= 0; i--) {
        for (int j = (i == top ? bit : 63); j >= 0; j--) {
            fp2_sqr(&result, &result);
            if ((e[i] >> j) & 1) fp2_mul(&result, &result, &base);
        }
    }
    *r = result;
}

static int fp2_is_square(const fp2_t *a) {
    if (fp2_is_zero(a)) return 1;
    fp_t n0, n1, norm, leg;
    fp_sqr(&n0, &a->c0);
    fp_sqr(&n1, &a->c1);
    fp_add(&norm, &n0, &n1);
    fp_pow(&leg, &norm, FB_P_MINUS_1_DIV_2);
    fp_t one; memcpy(one.d, FB_R1, sizeof one.d);
    return fp_equal(&leg, &one);
}

/* complex-extension sqrt for p % 4 == 3 (oracle Fq2.sqrt) */
static int fp2_sqrt(fp2_t *r, const fp2_t *a) {
    if (fp2_is_zero(a)) { fp2_zero(r); return 1; }
    fp2_t a1, alpha, x0, cand;
    fp2_pow(&a1, a, FB_P_MINUS_3_DIV_4);
    fp2_sqr(&alpha, &a1);
    fp2_mul(&alpha, &alpha, a);
    fp2_mul(&x0, &a1, a);
    fp2_t minus_one;
    fp2_one(&minus_one);
    fp_t z = FP_ZERO;
    fp_sub(&minus_one.c0, &z, &minus_one.c0); /* -1 */
    if (fp2_equal(&alpha, &minus_one)) {
        /* cand = i * x0 = (-x0.c1, x0.c0) */
        fp_neg(&cand.c0, &x0.c1);
        cand.c1 = x0.c0;
    } else {
        fp2_t b, one;
        fp2_one(&one);
        fp2_add(&b, &alpha, &one);
        fp2_pow(&b, &b, FB_P_MINUS_1_DIV_2);
        fp2_mul(&cand, &b, &x0);
    }
    fp2_t chk;
    fp2_sqr(&chk, &cand);
    if (!fp2_equal(&chk, a)) return 0;
    *r = cand;
    return 1;
}

/* RFC 9380 sgn0 for m=2 */
static int fp2_sgn0(const fp2_t *a) {
    int sign0 = fp_is_odd(&a->c0);
    int zero0 = fp_is_zero(&a->c0);
    int sign1 = fp_is_odd(&a->c1);
    return sign0 | (zero0 & sign1);
}

/* lexicographic greater for G2 y sign (c1 first, then c0) */
static int fp2_is_lex_greater(const fp2_t *a) {
    if (!fp_is_zero(&a->c1)) return fp_is_lex_greater(&a->c1);
    return fp_is_lex_greater(&a->c0);
}

/* ---------------------------------------------------------------- fp6 -- */

static void fp6_zero(fp6_t *r) { fp2_zero(&r->c0); fp2_zero(&r->c1); fp2_zero(&r->c2); }
static void fp6_one(fp6_t *r) { fp2_one(&r->c0); fp2_zero(&r->c1); fp2_zero(&r->c2); }
static int fp6_is_zero(const fp6_t *a) {
    return fp2_is_zero(&a->c0) && fp2_is_zero(&a->c1) && fp2_is_zero(&a->c2);
}
static void fp6_add(fp6_t *r, const fp6_t *a, const fp6_t *b) {
    fp2_add(&r->c0, &a->c0, &b->c0);
    fp2_add(&r->c1, &a->c1, &b->c1);
    fp2_add(&r->c2, &a->c2, &b->c2);
}
static void fp6_sub(fp6_t *r, const fp6_t *a, const fp6_t *b) {
    fp2_sub(&r->c0, &a->c0, &b->c0);
    fp2_sub(&r->c1, &a->c1, &b->c1);
    fp2_sub(&r->c2, &a->c2, &b->c2);
}
static void fp6_neg(fp6_t *r, const fp6_t *a) {
    fp2_neg(&r->c0, &a->c0);
    fp2_neg(&r->c1, &a->c1);
    fp2_neg(&r->c2, &a->c2);
}

/* Devegili et al. interleaved Karatsuba (6 fp2 muls) */
static void fp6_mul(fp6_t *r, const fp6_t *a, const fp6_t *b) {
    fp2_t v0, v1, v2, t0, t1, t2, s;
    fp2_mul(&v0, &a->c0, &b->c0);
    fp2_mul(&v1, &a->c1, &b->c1);
    fp2_mul(&v2, &a->c2, &b->c2);
    /* c0 = v0 + xi((a1+a2)(b1+b2) - v1 - v2) */
    fp2_add(&t0, &a->c1, &a->c2);
    fp2_add(&t1, &b->c1, &b->c2);
    fp2_mul(&s, &t0, &t1);
    fp2_sub(&s, &s, &v1);
    fp2_sub(&s, &s, &v2);
    fp2_mul_xi(&s, &s);
    fp2_add(&t2, &s, &v0); /* new c0 */
    /* c1 = (a0+a1)(b0+b1) - v0 - v1 + xi v2 */
    fp2_t c1;
    fp2_add(&t0, &a->c0, &a->c1);
    fp2_add(&t1, &b->c0, &b->c1);
    fp2_mul(&c1, &t0, &t1);
    fp2_sub(&c1, &c1, &v0);
    fp2_sub(&c1, &c1, &v1);
    fp2_mul_xi(&s, &v2);
    fp2_add(&c1, &c1, &s);
    /* c2 = (a0+a2)(b0+b2) - v0 - v2 + v1 */
    fp2_t c2;
    fp2_add(&t0, &a->c0, &a->c2);
    fp2_add(&t1, &b->c0, &b->c2);
    fp2_mul(&c2, &t0, &t1);
    fp2_sub(&c2, &c2, &v0);
    fp2_sub(&c2, &c2, &v2);
    fp2_add(&c2, &c2, &v1);
    r->c0 = t2;
    r->c1 = c1;
    r->c2 = c2;
}

static void fp6_sqr(fp6_t *r, const fp6_t *a) { fp6_mul(r, a, a); }

/* multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1) */
static void fp6_mul_by_v(fp6_t *r, const fp6_t *a) {
    fp2_t t;
    fp2_mul_xi(&t, &a->c2);
    r->c2 = a->c1;
    r->c1 = a->c0;
    r->c0 = t;
}

static void fp6_inv(fp6_t *r, const fp6_t *a) {
    fp2_t c0, c1, c2, t0, t1, t;
    /* c0 = a0^2 - xi a1 a2 */
    fp2_sqr(&c0, &a->c0);
    fp2_mul(&t0, &a->c1, &a->c2);
    fp2_mul_xi(&t0, &t0);
    fp2_sub(&c0, &c0, &t0);
    /* c1 = xi a2^2 - a0 a1 */
    fp2_sqr(&c1, &a->c2);
    fp2_mul_xi(&c1, &c1);
    fp2_mul(&t0, &a->c0, &a->c1);
    fp2_sub(&c1, &c1, &t0);
    /* c2 = a1^2 - a0 a2 */
    fp2_sqr(&c2, &a->c1);
    fp2_mul(&t0, &a->c0, &a->c2);
    fp2_sub(&c2, &c2, &t0);
    /* t = a0 c0 + xi (a1 c2 + a2 c1) */
    fp2_mul(&t0, &a->c1, &c2);
    fp2_mul(&t1, &a->c2, &c1);
    fp2_add(&t0, &t0, &t1);
    fp2_mul_xi(&t0, &t0);
    fp2_mul(&t, &a->c0, &c0);
    fp2_add(&t, &t, &t0);
    fp2_inv(&t, &t);
    fp2_mul(&r->c0, &c0, &t);
    fp2_mul(&r->c1, &c1, &t);
    fp2_mul(&r->c2, &c2, &t);
}

static void fp6_frobenius(fp6_t *r, const fp6_t *a) {
    fp2_t t;
    fp2_conj(&r->c0, &a->c0);
    fp2_conj(&t, &a->c1);
    fp2_mul(&r->c1, &t, FP2_P_FROB_V);
    fp2_conj(&t, &a->c2);
    fp2_mul(&r->c2, &t, FP2_P_FROB_V2);
}

/* --------------------------------------------------------------- fp12 -- */

static void fp12_one(fp12_t *r) { fp6_one(&r->c0); fp6_zero(&r->c1); }
static int fp12_is_one(const fp12_t *a) {
    fp12_t one;
    fp12_one(&one);
    if (!fp6_is_zero(&a->c1)) return 0;
    return fp2_equal(&a->c0.c0, &one.c0.c0) && fp2_is_zero(&a->c0.c1) && fp2_is_zero(&a->c0.c2);
}

static void fp12_mul(fp12_t *r, const fp12_t *a, const fp12_t *b) {
    fp6_t v0, v1, t0, t1;
    fp6_mul(&v0, &a->c0, &b->c0);
    fp6_mul(&v1, &a->c1, &b->c1);
    /* c1 = (a0+a1)(b0+b1) - v0 - v1 */
    fp6_add(&t0, &a->c0, &a->c1);
    fp6_add(&t1, &b->c0, &b->c1);
    fp6_mul(&t0, &t0, &t1);
    fp6_sub(&t0, &t0, &v0);
    fp6_sub(&t0, &t0, &v1);
    /* c0 = v0 + v*v1 */
    fp6_mul_by_v(&t1, &v1);
    fp6_add(&r->c0, &v0, &t1);
    r->c1 = t0;
}

static void fp12_sqr(fp12_t *r, const fp12_t *a) { fp12_mul(r, a, a); }

static void fp12_conj(fp12_t *r, const fp12_t *a) {
    r->c0 = a->c0;
    fp6_neg(&r->c1, &a->c1);
}

static void fp12_inv(fp12_t *r, const fp12_t *a) {
    /* (a0 + a1 w)^-1 = (a0 - a1 w) / (a0^2 - v a1^2) */
    fp6_t t0, t1;
    fp6_sqr(&t0, &a->c0);
    fp6_sqr(&t1, &a->c1);
    fp6_mul_by_v(&t1, &t1);
    fp6_sub(&t0, &t0, &t1);
    fp6_inv(&t0, &t0);
    fp6_mul(&r->c0, &a->c0, &t0);
    fp6_mul(&t1, &a->c1, &t0);
    fp6_neg(&r->c1, &t1);
}

static void fp12_frobenius(fp12_t *r, const fp12_t *a) {
    fp6_t t;
    fp6_frobenius(&r->c0, &a->c0);
    fp6_frobenius(&t, &a->c1);
    fp2_mul(&r->c1.c0, &t.c0, FP2_P_FROB_W);
    fp2_mul(&r->c1.c1, &t.c1, FP2_P_FROB_W);
    fp2_mul(&r->c1.c2, &t.c2, FP2_P_FROB_W);
}

/* f^|z| by plain square-and-multiply over the 64-bit parameter;
 * then conjugate (z < 0, cyclotomic inverse = conjugate). */
static void fp12_pow_x(fp12_t *r, const fp12_t *a) {
    fp12_t result = *a; /* leading bit consumed */
    for (int bit = 62; bit >= 0; bit--) {
        fp12_sqr(&result, &result);
        if ((FB_X_ABS >> bit) & 1) fp12_mul(&result, &result, a);
    }
    fp12_conj(r, &result); /* negative parameter */
}

/* f^(3 * (p^12-1)/r) via easy part + BLS12 x-chain (ops/pairing.py
 * final_exponentiation; the cube is harmless for verdicts). */
static void fp12_final_exp(fp12_t *r, const fp12_t *f) {
    fp12_t f1, inv, m, y0, y1, y2, y3, t, t2;
    /* easy: f^(p^6-1) = conj(f) * inv(f); then ^(p^2+1) */
    fp12_conj(&f1, f);
    fp12_inv(&inv, f);
    fp12_mul(&f1, &f1, &inv);
    fp12_frobenius(&m, &f1);
    fp12_frobenius(&m, &m);
    fp12_mul(&m, &m, &f1);
    /* hard: ((x-1)^2 (x+p) (x^2+p^2-1) + 3) */
    fp12_pow_x(&y0, &m);
    fp12_conj(&t, &m);
    fp12_mul(&y0, &y0, &t); /* m^(x-1) */
    fp12_pow_x(&y1, &y0);
    fp12_conj(&t, &y0);
    fp12_mul(&y1, &y1, &t); /* m^((x-1)^2) */
    fp12_pow_x(&y2, &y1);
    fp12_frobenius(&t, &y1);
    fp12_mul(&y2, &y2, &t); /* ^(x+p) */
    fp12_pow_x(&y3, &y2);
    fp12_pow_x(&y3, &y3);
    fp12_frobenius(&t, &y2);
    fp12_frobenius(&t, &t);
    fp12_mul(&y3, &y3, &t);
    fp12_conj(&t, &y2);
    fp12_mul(&y3, &y3, &t); /* ^(x^2+p^2-1) */
    fp12_sqr(&t2, &m);
    fp12_mul(&t2, &t2, &m); /* m^3 */
    fp12_mul(r, &y3, &t2);
}

/* ------------------------------------------------------------ G1 / G2 -- */

static void g1_infinity(g1_t *r) {
    memcpy(r->x.d, FB_R1, sizeof r->x.d);
    memcpy(r->y.d, FB_R1, sizeof r->y.d);
    r->z = FP_ZERO;
}
static int g1_is_infinity(const g1_t *a) { return fp_is_zero(&a->z); }

static void g1_double(g1_t *r, const g1_t *p) {
    if (g1_is_infinity(p)) { *r = *p; return; }
    fp_t a, b, c, d, e, f, t, x3, y3, z3;
    fp_sqr(&a, &p->x);
    fp_sqr(&b, &p->y);
    fp_sqr(&c, &b);
    fp_add(&t, &p->x, &b);
    fp_sqr(&t, &t);
    fp_sub(&t, &t, &a);
    fp_sub(&t, &t, &c);
    fp_dbl(&d, &t);
    fp_dbl(&e, &a);
    fp_add(&e, &e, &a);
    fp_sqr(&f, &e);
    fp_sub(&x3, &f, &d);
    fp_sub(&x3, &x3, &d);
    fp_sub(&t, &d, &x3);
    fp_mul(&y3, &e, &t);
    fp_dbl(&c, &c); fp_dbl(&c, &c); fp_dbl(&c, &c); /* 8C */
    fp_sub(&y3, &y3, &c);
    fp_mul(&z3, &p->y, &p->z);
    fp_dbl(&z3, &z3);
    r->x = x3; r->y = y3; r->z = z3;
}

static void g1_add(g1_t *r, const g1_t *p, const g1_t *q) {
    if (g1_is_infinity(p)) { *r = *q; return; }
    if (g1_is_infinity(q)) { *r = *p; return; }
    fp_t z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t, x3, y3, z3;
    fp_sqr(&z1z1, &p->z);
    fp_sqr(&z2z2, &q->z);
    fp_mul(&u1, &p->x, &z2z2);
    fp_mul(&u2, &q->x, &z1z1);
    fp_mul(&s1, &p->y, &q->z); fp_mul(&s1, &s1, &z2z2);
    fp_mul(&s2, &q->y, &p->z); fp_mul(&s2, &s2, &z1z1);
    if (fp_equal(&u1, &u2)) {
        if (fp_equal(&s1, &s2)) { g1_double(r, p); return; }
        g1_infinity(r); return;
    }
    fp_sub(&h, &u2, &u1);
    fp_dbl(&i, &h);
    fp_sqr(&i, &i);
    fp_mul(&j, &h, &i);
    fp_sub(&rr, &s2, &s1);
    fp_dbl(&rr, &rr);
    fp_mul(&v, &u1, &i);
    fp_sqr(&x3, &rr);
    fp_sub(&x3, &x3, &j);
    fp_sub(&x3, &x3, &v);
    fp_sub(&x3, &x3, &v);
    fp_sub(&t, &v, &x3);
    fp_mul(&y3, &rr, &t);
    fp_mul(&t, &s1, &j);
    fp_dbl(&t, &t);
    fp_sub(&y3, &y3, &t);
    fp_add(&z3, &p->z, &q->z);
    fp_sqr(&z3, &z3);
    fp_sub(&z3, &z3, &z1z1);
    fp_sub(&z3, &z3, &z2z2);
    fp_mul(&z3, &z3, &h);
    r->x = x3; r->y = y3; r->z = z3;
}

static void g1_neg(g1_t *r, const g1_t *p) {
    r->x = p->x;
    fp_neg(&r->y, &p->y);
    r->z = p->z;
}

/* scalar given as 4 LE limbs (up to 256 bits) */
static void g1_mul(g1_t *r, const g1_t *p, const uint64_t e[4]) {
    g1_t acc;
    g1_infinity(&acc);
    int top = 3;
    while (top >= 0 && e[top] == 0) top--;
    if (top < 0) { *r = acc; return; }
    int bit = 63;
    while (!((e[top] >> bit) & 1)) bit--;
    for (int i = top; i >= 0; i--) {
        for (int j = (i == top ? bit : 63); j >= 0; j--) {
            g1_double(&acc, &acc);
            if ((e[i] >> j) & 1) g1_add(&acc, &acc, p);
        }
    }
    *r = acc;
}

/* -> affine; returns 0 for infinity */
static int g1_to_affine(fp_t *x, fp_t *y, const g1_t *p) {
    if (g1_is_infinity(p)) return 0;
    fp_t zi, zi2, zi3;
    fp_inv(&zi, &p->z);
    fp_sqr(&zi2, &zi);
    fp_mul(&zi3, &zi2, &zi);
    fp_mul(x, &p->x, &zi2);
    fp_mul(y, &p->y, &zi3);
    return 1;
}

static int g1_on_curve(const fp_t *x, const fp_t *y) {
    fp_t l, rr, b;
    fp_sqr(&l, y);
    fp_sqr(&rr, x);
    fp_mul(&rr, &rr, x);
    memcpy(b.d, FB_B1, sizeof b.d);
    fp_add(&rr, &rr, &b);
    return fp_equal(&l, &rr);
}

static int g1_equal(const g1_t *a, const g1_t *b) {
    int ia = g1_is_infinity(a), ib = g1_is_infinity(b);
    if (ia || ib) return ia && ib;
    /* cross-multiplied jacobian comparison */
    fp_t za2, zb2, za3, zb3, t0, t1;
    fp_sqr(&za2, &a->z);
    fp_sqr(&zb2, &b->z);
    fp_mul(&t0, &a->x, &zb2);
    fp_mul(&t1, &b->x, &za2);
    if (!fp_equal(&t0, &t1)) return 0;
    fp_mul(&za3, &za2, &a->z);
    fp_mul(&zb3, &zb2, &b->z);
    fp_mul(&t0, &a->y, &zb3);
    fp_mul(&t1, &b->y, &za3);
    return fp_equal(&t0, &t1);
}

/* G1 subgroup check via the sigma endomorphism: sigma(P) == [z^2-1]P */
static int g1_subgroup_check(const g1_t *p) {
    if (g1_is_infinity(p)) return 1;
    fp_t ax, ay;
    g1_to_affine(&ax, &ay, p);
    g1_t sigma;
    fp_t beta; memcpy(beta.d, FB_BETA, sizeof beta.d);
    fp_mul(&sigma.x, &ax, &beta);
    sigma.y = ay;
    memcpy(sigma.z.d, FB_R1, sizeof sigma.z.d);
    /* z^2 - 1 with z = -|x|: z^2 - 1 = x^2 - 1 */
    unsigned __int128 x2 = (unsigned __int128)FB_X_ABS * FB_X_ABS - 1;
    uint64_t e[4] = {(uint64_t)x2, (uint64_t)(x2 >> 64), 0, 0};
    g1_t zp;
    g1_mul(&zp, p, e);
    return g1_equal(&sigma, &zp);
}

/* G2 mirrors of all of the above */

static void g2_infinity(g2_t *r) {
    fp2_one(&r->x);
    fp2_one(&r->y);
    fp2_zero(&r->z);
}
static int g2_is_infinity(const g2_t *a) { return fp2_is_zero(&a->z); }

static void g2_double(g2_t *r, const g2_t *p) {
    if (g2_is_infinity(p)) { *r = *p; return; }
    fp2_t a, b, c, d, e, f, t, x3, y3, z3;
    fp2_sqr(&a, &p->x);
    fp2_sqr(&b, &p->y);
    fp2_sqr(&c, &b);
    fp2_add(&t, &p->x, &b);
    fp2_sqr(&t, &t);
    fp2_sub(&t, &t, &a);
    fp2_sub(&t, &t, &c);
    fp2_dbl(&d, &t);
    fp2_dbl(&e, &a);
    fp2_add(&e, &e, &a);
    fp2_sqr(&f, &e);
    fp2_sub(&x3, &f, &d);
    fp2_sub(&x3, &x3, &d);
    fp2_sub(&t, &d, &x3);
    fp2_mul(&y3, &e, &t);
    fp2_dbl(&c, &c); fp2_dbl(&c, &c); fp2_dbl(&c, &c);
    fp2_sub(&y3, &y3, &c);
    fp2_mul(&z3, &p->y, &p->z);
    fp2_dbl(&z3, &z3);
    r->x = x3; r->y = y3; r->z = z3;
}

static void g2_add(g2_t *r, const g2_t *p, const g2_t *q) {
    if (g2_is_infinity(p)) { *r = *q; return; }
    if (g2_is_infinity(q)) { *r = *p; return; }
    fp2_t z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t, x3, y3, z3;
    fp2_sqr(&z1z1, &p->z);
    fp2_sqr(&z2z2, &q->z);
    fp2_mul(&u1, &p->x, &z2z2);
    fp2_mul(&u2, &q->x, &z1z1);
    fp2_mul(&s1, &p->y, &q->z); fp2_mul(&s1, &s1, &z2z2);
    fp2_mul(&s2, &q->y, &p->z); fp2_mul(&s2, &s2, &z1z1);
    if (fp2_equal(&u1, &u2)) {
        if (fp2_equal(&s1, &s2)) { g2_double(r, p); return; }
        g2_infinity(r); return;
    }
    fp2_sub(&h, &u2, &u1);
    fp2_dbl(&i, &h);
    fp2_sqr(&i, &i);
    fp2_mul(&j, &h, &i);
    fp2_sub(&rr, &s2, &s1);
    fp2_dbl(&rr, &rr);
    fp2_mul(&v, &u1, &i);
    fp2_sqr(&x3, &rr);
    fp2_sub(&x3, &x3, &j);
    fp2_sub(&x3, &x3, &v);
    fp2_sub(&x3, &x3, &v);
    fp2_sub(&t, &v, &x3);
    fp2_mul(&y3, &rr, &t);
    fp2_mul(&t, &s1, &j);
    fp2_dbl(&t, &t);
    fp2_sub(&y3, &y3, &t);
    fp2_add(&z3, &p->z, &q->z);
    fp2_sqr(&z3, &z3);
    fp2_sub(&z3, &z3, &z1z1);
    fp2_sub(&z3, &z3, &z2z2);
    fp2_mul(&z3, &z3, &h);
    r->x = x3; r->y = y3; r->z = z3;
}

static void g2_neg(g2_t *r, const g2_t *p) {
    r->x = p->x;
    fp2_neg(&r->y, &p->y);
    r->z = p->z;
}

static void g2_mul(g2_t *r, const g2_t *p, const uint64_t e[4]) {
    g2_t acc;
    g2_infinity(&acc);
    int top = 3;
    while (top >= 0 && e[top] == 0) top--;
    if (top < 0) { *r = acc; return; }
    int bit = 63;
    while (!((e[top] >> bit) & 1)) bit--;
    for (int i = top; i >= 0; i--) {
        for (int j = (i == top ? bit : 63); j >= 0; j--) {
            g2_double(&acc, &acc);
            if ((e[i] >> j) & 1) g2_add(&acc, &acc, p);
        }
    }
    *r = acc;
}

/* branchless r = bit ? a : b over the 36 limbs (3 fp2 = 6 fp x 6 limbs)
 * of a jacobian g2 point */
static void g2_csel(g2_t *r, const g2_t *a, const g2_t *b, uint64_t bit) {
    uint64_t mask = (uint64_t)0 - (bit & 1);
    const uint64_t *pa = (const uint64_t *)a;
    const uint64_t *pb = (const uint64_t *)b;
    uint64_t *pr = (uint64_t *)r;
    for (size_t i = 0; i < sizeof(g2_t) / sizeof(uint64_t); i++)
        pr[i] = (pa[i] & mask) | (pb[i] & ~mask);
}

/* out = e + r (+ r again, branchlessly, while bit 255 is still clear).
 * For e in [1, r): out == e (mod r), out < 2^256, and bit 255 is ALWAYS
 * set — so a fixed 256-bit ladder can start from a known top bit and
 * never touch the infinity point, independent of e.  (r ~ 0.45 * 2^256:
 * e + r never carries out of 4 limbs, and the second add only happens
 * when e + r < 2^255, which bounds e + 2r < 2^256.) */
static void scalar_fix256(uint64_t out[4], const uint64_t e[4]) {
    unsigned __int128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (unsigned __int128)e[i] + FB_ORDER[i];
        out[i] = (uint64_t)c;
        c >>= 64;
    }
    uint64_t mask = (uint64_t)0 - (1 ^ (out[3] >> 63));
    c = 0;
    for (int i = 0; i < 4; i++) {
        c += (unsigned __int128)out[i] + (FB_ORDER[i] & mask);
        out[i] = (uint64_t)c;
        c >>= 64;
    }
}

/* Scalar mult with a UNIFORM operation sequence: fixed-length ladder
 * (scalar_fix256 pins the top bit), one double + one add + one branchless
 * select per bit — unlike g2_mul above, no per-bit branch and no
 * scalar-dependent iteration count, so the timing/branch trace does not
 * encode the secret scalar.  Residual caveats, stated honestly: the
 * exceptional-case branches inside g2_add (acc == +-p, i.e. a ladder
 * prefix ~ +-1 mod r) fire with probability ~2^-254 for uniform secrets,
 * and the Montgomery fp core is data-independent in operation sequence
 * but not audited to asm level.  This is the double-and-always-add
 * discipline production signers need; the sliding g2_mul stays for
 * verification work on PUBLIC points where speed matters. */
static void g2_mul_ct(g2_t *r, const g2_t *p, const uint64_t e[4]) {
    uint64_t k[4];
    g2_t acc, sum;
    scalar_fix256(k, e);
    acc = *p; /* top bit (255) is always set */
    for (int i = 254; i >= 0; i--) {
        g2_double(&acc, &acc);
        g2_add(&sum, &acc, p);
        g2_csel(&acc, &sum, &acc, (k[i >> 6] >> (i & 63)) & 1);
    }
    *r = acc;
}

static int g2_to_affine(fp2_t *x, fp2_t *y, const g2_t *p) {
    if (g2_is_infinity(p)) return 0;
    fp2_t zi, zi2, zi3;
    fp2_inv(&zi, &p->z);
    fp2_sqr(&zi2, &zi);
    fp2_mul(&zi3, &zi2, &zi);
    fp2_mul(x, &p->x, &zi2);
    fp2_mul(y, &p->y, &zi3);
    return 1;
}

static int g2_on_curve(const fp2_t *x, const fp2_t *y) {
    fp2_t l, rr;
    const fp2_t *b2 = (const fp2_t *)FB_B2;
    fp2_sqr(&l, y);
    fp2_sqr(&rr, x);
    fp2_mul(&rr, &rr, x);
    fp2_add(&rr, &rr, b2);
    return fp2_equal(&l, &rr);
}

static int g2_equal(const g2_t *a, const g2_t *b) {
    int ia = g2_is_infinity(a), ib = g2_is_infinity(b);
    if (ia || ib) return ia && ib;
    fp2_t za2, zb2, za3, zb3, t0, t1;
    fp2_sqr(&za2, &a->z);
    fp2_sqr(&zb2, &b->z);
    fp2_mul(&t0, &a->x, &zb2);
    fp2_mul(&t1, &b->x, &za2);
    if (!fp2_equal(&t0, &t1)) return 0;
    fp2_mul(&za3, &za2, &a->z);
    fp2_mul(&zb3, &zb2, &b->z);
    fp2_mul(&t0, &a->y, &zb3);
    fp2_mul(&t1, &b->y, &za3);
    return fp2_equal(&t0, &t1);
}

/* psi endomorphism on affine coords (curve.py psi) */
static void g2_psi_affine(fp2_t *rx, fp2_t *ry, const fp2_t *x, const fp2_t *y) {
    fp2_t t;
    fp2_conj(&t, x);
    fp2_mul(rx, &t, (const fp2_t *)FB_PSI_CX);
    fp2_conj(&t, y);
    fp2_mul(ry, &t, (const fp2_t *)FB_PSI_CY);
}

static void g2_psi(g2_t *r, const g2_t *p) {
    if (g2_is_infinity(p)) { *r = *p; return; }
    fp2_t x, y, px, py;
    g2_to_affine(&x, &y, p);
    g2_psi_affine(&px, &py, &x, &y);
    r->x = px;
    r->y = py;
    fp2_one(&r->z);
}

/* G2 subgroup: psi(P) == [z]P = -[|z|]P */
static int g2_subgroup_check(const g2_t *p) {
    if (g2_is_infinity(p)) return 1;
    g2_t psi_p, zp;
    g2_psi(&psi_p, p);
    uint64_t e[4] = {FB_X_ABS, 0, 0, 0};
    g2_mul(&zp, p, e);
    g2_neg(&zp, &zp);
    return g2_equal(&psi_p, &zp);
}

/* Budroni-Pintore cofactor clearing:
 * h_eff P = [z^2-z-1]P + [z-1]psi(P) + psi^2([2]P), z = -|x| */
static void g2_clear_cofactor(g2_t *r, const g2_t *p) {
    /* z^2 - z - 1 = x^2 + x - 1 (positive, ~128 bits) */
    unsigned __int128 s = (unsigned __int128)FB_X_ABS * FB_X_ABS + FB_X_ABS - 1;
    uint64_t e1[4] = {(uint64_t)s, (uint64_t)(s >> 64), 0, 0};
    g2_t t1, t2, t3, psi_p, d;
    g2_mul(&t1, p, e1);
    /* [z-1]P = -[|x|+1]P */
    uint64_t e2[4] = {FB_X_ABS + 1, 0, 0, 0};
    g2_psi(&psi_p, p);
    g2_mul(&t2, &psi_p, e2);
    g2_neg(&t2, &t2);
    g2_double(&d, p);
    g2_psi(&t3, &d);
    g2_psi(&t3, &t3);
    g2_add(r, &t1, &t2);
    g2_add(r, r, &t3);
}

/* ------------------------------------------------------ decompression -- */

/* ZCash compressed format; returns 1 ok, 0 malformed/not-on-curve.
 * subgroup check is separate (callers decide). infinity -> z = 0. */
static int g1_from_compressed(g1_t *r, const uint8_t *in) {
    uint8_t flags = in[0];
    if (!(flags & 0x80)) return 0;
    if (flags & 0x40) {
        if (flags != 0xC0) return 0;
        for (int i = 1; i < 48; i++) if (in[i]) return 0;
        g1_infinity(r);
        return 1;
    }
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1F;
    fp_t x, y2, y, b;
    if (!fp_from_bytes(&x, buf)) return 0;
    fp_sqr(&y2, &x);
    fp_mul(&y2, &y2, &x);
    memcpy(b.d, FB_B1, sizeof b.d);
    fp_add(&y2, &y2, &b);
    if (!fp_sqrt(&y, &y2)) return 0;
    if (fp_is_lex_greater(&y) != !!(flags & 0x20)) fp_neg(&y, &y);
    r->x = x;
    r->y = y;
    memcpy(r->z.d, FB_R1, sizeof r->z.d);
    return 1;
}

static int g2_from_compressed(g2_t *r, const uint8_t *in) {
    uint8_t flags = in[0];
    if (!(flags & 0x80)) return 0;
    if (flags & 0x40) {
        if (flags != 0xC0) return 0;
        for (int i = 1; i < 96; i++) if (in[i]) return 0;
        g2_infinity(r);
        return 1;
    }
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1F;
    fp2_t x, y2, y;
    if (!fp_from_bytes(&x.c1, buf)) return 0;   /* c1 first on the wire */
    if (!fp_from_bytes(&x.c0, in + 48)) return 0;
    fp2_sqr(&y2, &x);
    fp2_mul(&y2, &y2, &x);
    fp2_add(&y2, &y2, (const fp2_t *)FB_B2);
    if (!fp2_sqrt(&y, &y2)) return 0;
    if (fp2_is_lex_greater(&y) != !!(flags & 0x20)) fp2_neg(&y, &y);
    r->x = x;
    r->y = y;
    fp2_one(&r->z);
    return 1;
}

/* -------------------------------------------------------------- sha256 -- */

static const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

typedef struct {
    uint32_t h[8];
    uint64_t len;
    uint8_t buf[64];
    size_t buflen;
} sha256_ctx;

static void sha256_init(sha256_ctx *c) {
    static const uint32_t h0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    memcpy(c->h, h0, sizeof h0);
    c->len = 0;
    c->buflen = 0;
}

static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

static void sha256_block(sha256_ctx *c, const uint8_t *p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
               ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3];
    uint32_t e = c->h[4], f = c->h[5], g = c->h[6], h = c->h[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + SHA_K[i] + w[i];
        uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
        uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = cc; cc = b; b = a; a = t1 + t2;
    }
    c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
    c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void sha256_update(sha256_ctx *c, const uint8_t *p, size_t n) {
    c->len += n;
    while (n) {
        if (c->buflen == 0 && n >= 64) {
            sha256_block(c, p);
            p += 64;
            n -= 64;
        } else {
            size_t take = 64 - c->buflen;
            if (take > n) take = n;
            memcpy(c->buf + c->buflen, p, take);
            c->buflen += take;
            p += take;
            n -= take;
            if (c->buflen == 64) {
                sha256_block(c, c->buf);
                c->buflen = 0;
            }
        }
    }
}

static void sha256_final(sha256_ctx *c, uint8_t out[32]) {
    uint64_t bits = c->len * 8;
    uint8_t pad = 0x80;
    sha256_update(c, &pad, 1);
    uint8_t z = 0;
    while (c->buflen != 56) sha256_update(c, &z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (8 * (7 - i)));
    sha256_update(c, lb, 8);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)(c->h[i] >> 24);
        out[4 * i + 1] = (uint8_t)(c->h[i] >> 16);
        out[4 * i + 2] = (uint8_t)(c->h[i] >> 8);
        out[4 * i + 3] = (uint8_t)c->h[i];
    }
}

/* ------------------------------------------------------- hash-to-G2 ---- */

static const char DST[] = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_";
#define DST_LEN 43
#define HTF_L 64 /* bytes per draw */

/* expand_message_xmd for len_in_bytes = 256 (count=2, m=2, L=64) */
static void expand_message_256(uint8_t out[256], const uint8_t *msg, size_t msg_len) {
    uint8_t b0[32], bi[32];
    sha256_ctx c;
    static const uint8_t z_pad[64] = {0};
    uint8_t lib[3] = {0x01, 0x00, 0x00}; /* 256 big-endian, then i2osp(0,1) */
    uint8_t dst_prime[DST_LEN + 1];
    memcpy(dst_prime, DST, DST_LEN);
    dst_prime[DST_LEN] = DST_LEN;
    sha256_init(&c);
    sha256_update(&c, z_pad, 64);
    sha256_update(&c, msg, msg_len);
    sha256_update(&c, lib, 3);
    sha256_update(&c, dst_prime, DST_LEN + 1);
    sha256_final(&c, b0);
    uint8_t one = 1;
    sha256_init(&c);
    sha256_update(&c, b0, 32);
    sha256_update(&c, &one, 1);
    sha256_update(&c, dst_prime, DST_LEN + 1);
    sha256_final(&c, bi);
    memcpy(out, bi, 32);
    for (int i = 2; i <= 8; i++) {
        uint8_t tmp[32];
        for (int j = 0; j < 32; j++) tmp[j] = b0[j] ^ bi[j];
        uint8_t idx = (uint8_t)i;
        sha256_init(&c);
        sha256_update(&c, tmp, 32);
        sha256_update(&c, &idx, 1);
        sha256_update(&c, dst_prime, DST_LEN + 1);
        sha256_final(&c, bi);
        memcpy(out + 32 * (i - 1), bi, 32);
    }
}

/* reduce a 64-byte big-endian integer mod p into mont form */
static void fp_from_be64_reduce(fp_t *r, const uint8_t *in) {
    /* v = hi * 2^128 + lo, hi 48 bytes, lo 16 bytes:
     * process as base-2^64 digits with Montgomery-free reduction via
     * repeated (shift 64 + add) using fp arithmetic on mont values:
     * simpler: accumulate byte-by-byte: r = r*256 + byte (in mont form). */
    fp_t acc = FP_ZERO, t256, byte_v;
    fp_t r256 = FP_ZERO;
    r256.d[0] = 256;
    fp_to_mont(&t256, &r256);
    for (int i = 0; i < 64; i++) {
        fp_mul(&acc, &acc, &t256);
        fp_t bv = FP_ZERO;
        bv.d[0] = in[i];
        fp_to_mont(&byte_v, &bv);
        fp_add(&acc, &acc, &byte_v);
    }
    *r = acc;
}

/* g'(x) = x^3 + A'x + B' on the isogenous curve */
static void sswu_gprime(fp2_t *r, const fp2_t *x) {
    fp2_t t, ax;
    fp2_sqr(&t, x);
    fp2_mul(&t, &t, x);
    fp2_mul(&ax, (const fp2_t *)FB_ISO_A, x);
    fp2_add(&t, &t, &ax);
    fp2_add(r, &t, (const fp2_t *)FB_ISO_B);
}

/* simplified SWU onto E' (oracle map_to_curve_sswu) */
static void sswu_map(fp2_t *xo, fp2_t *yo, const fp2_t *u) {
    const fp2_t *Z = (const fp2_t *)FB_SSWU_Z;
    const fp2_t *A = (const fp2_t *)FB_ISO_A;
    const fp2_t *B = (const fp2_t *)FB_ISO_B;
    fp2_t u2, u4, z2, tv1, x1, gx1, one;
    fp2_one(&one);
    fp2_sqr(&u2, u);
    fp2_sqr(&u4, &u2);
    fp2_sqr(&z2, Z);
    fp2_mul(&tv1, &z2, &u4);
    fp2_t zu2;
    fp2_mul(&zu2, Z, &u2);
    fp2_add(&tv1, &tv1, &zu2);
    if (fp2_is_zero(&tv1)) {
        fp2_t za, zai;
        fp2_mul(&za, Z, A);
        fp2_inv(&zai, &za);
        fp2_mul(&x1, B, &zai);
    } else {
        fp2_t negb, ainv, inv1, s;
        fp2_neg(&negb, B);
        fp2_inv(&ainv, A);
        fp2_inv(&inv1, &tv1);
        fp2_add(&s, &one, &inv1);
        fp2_mul(&x1, &negb, &ainv);
        fp2_mul(&x1, &x1, &s);
    }
    sswu_gprime(&gx1, &x1);
    fp2_t x, y;
    if (fp2_is_square(&gx1)) {
        x = x1;
        fp2_sqrt(&y, &gx1);
    } else {
        fp2_t gx2;
        fp2_mul(&x, &zu2, &x1);
        sswu_gprime(&gx2, &x);
        fp2_sqrt(&y, &gx2);
    }
    if (fp2_sgn0(u) != fp2_sgn0(&y)) fp2_neg(&y, &y);
    *xo = x;
    *yo = y;
}

static void eval_poly(fp2_t *r, const uint64_t coeffs[][2][6], int n, const fp2_t *x) {
    fp2_t acc;
    fp2_zero(&acc);
    for (int i = n - 1; i >= 0; i--) {
        fp2_mul(&acc, &acc, x);
        fp2_add(&acc, &acc, (const fp2_t *)coeffs[i]);
    }
    *r = acc;
}

/* 3-isogeny E' -> E2 */
static void iso_map(fp2_t *xo, fp2_t *yo, const fp2_t *x, const fp2_t *y) {
    fp2_t xn, xd, yn, yd, xdi, ydi;
    eval_poly(&xn, FB_K1, 4, x);
    eval_poly(&xd, FB_K2, 3, x);
    eval_poly(&yn, FB_K3, 4, x);
    eval_poly(&yd, FB_K4, 4, x);
    fp2_inv(&xdi, &xd);
    fp2_inv(&ydi, &yd);
    fp2_mul(xo, &xn, &xdi);
    fp2_mul(yo, y, &yn);
    fp2_mul(yo, yo, &ydi);
}

/* full hash_to_g2 (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_) */
static void hash_to_g2(g2_t *r, const uint8_t *msg, size_t msg_len) {
    uint8_t uniform[256];
    expand_message_256(uniform, msg, msg_len);
    fp2_t u0, u1;
    fp_from_be64_reduce(&u0.c0, uniform);
    fp_from_be64_reduce(&u0.c1, uniform + 64);
    fp_from_be64_reduce(&u1.c0, uniform + 128);
    fp_from_be64_reduce(&u1.c1, uniform + 192);
    fp2_t x0, y0, x1, y1, xm, ym;
    g2_t q0, q1, q;
    sswu_map(&x0, &y0, &u0);
    iso_map(&xm, &ym, &x0, &y0);
    q0.x = xm; q0.y = ym; fp2_one(&q0.z);
    sswu_map(&x1, &y1, &u1);
    iso_map(&xm, &ym, &x1, &y1);
    q1.x = xm; q1.y = ym; fp2_one(&q1.z);
    g2_add(&q, &q0, &q1);
    g2_clear_cofactor(r, &q);
}

/* ------------------------------------------------------------ pairing -- */

/* line value as sparse fp12: (c0 + c1 v) + (c2 v) w */
static void line_to_fp12(fp12_t *r, const fp2_t *c0, const fp2_t *c1, const fp2_t *c2) {
    r->c0.c0 = *c0;
    r->c0.c1 = *c1;
    fp2_zero(&r->c0.c2);
    fp2_zero(&r->c1.c0);
    r->c1.c1 = *c2;
    fp2_zero(&r->c1.c2);
}

/* doubling step with tangent line (ops/pairing.py _dbl_step):
 * line scaled by 2YZ^3 (subfield factor, killed by final exp):
 *   c0 = 3X^3 - 2Y^2; c1 = -3X^2 Z^2 xp; c2 = 2YZ^3 yp */
static void miller_dbl_step(g2_t *t, fp12_t *line, const fp_t *xp, const fp_t *yp) {
    fp2_t x2, y2, z2, yz, x2_3, x3_3, c1r, yz3, c0, c1, c2, t2;
    fp2_sqr(&x2, &t->x);
    fp2_sqr(&y2, &t->y);
    fp2_sqr(&z2, &t->z);
    fp2_mul(&yz, &t->y, &t->z);
    fp2_dbl(&x2_3, &x2);
    fp2_add(&x2_3, &x2_3, &x2);
    fp2_mul(&x3_3, &x2_3, &t->x);
    fp2_mul(&c1r, &x2_3, &z2);
    fp2_mul(&yz3, &yz, &z2);
    fp2_dbl(&t2, &y2);
    fp2_sub(&c0, &x3_3, &t2);
    fp2_mul_fp(&c1, &c1r, xp);
    fp2_neg(&c1, &c1);
    fp2_dbl(&yz3, &yz3);
    fp2_mul_fp(&c2, &yz3, yp);
    line_to_fp12(line, &c0, &c1, &c2);
    g2_double(t, t);
}

/* addition step with the affine loop point Q (ops/pairing.py _add_step):
 * line scaled by Z*H: c0 = theta xq - yq Z H; c1 = -theta xp; c2 = Z H yp */
static void miller_add_step(g2_t *t, fp12_t *line, const fp2_t *xq, const fp2_t *yq,
                            const fp_t *xp, const fp_t *yp) {
    fp2_t zz, zzz, u2, s2, theta, h, zh, theta_xq, yq_zh, c0, c1, c2;
    fp2_sqr(&zz, &t->z);
    fp2_mul(&zzz, &zz, &t->z);
    fp2_mul(&u2, xq, &zz);
    fp2_mul(&s2, yq, &zzz);
    fp2_sub(&theta, &t->y, &s2);
    fp2_sub(&h, &t->x, &u2);
    fp2_mul(&zh, &t->z, &h);
    fp2_mul(&theta_xq, &theta, xq);
    fp2_mul(&yq_zh, yq, &zh);
    fp2_sub(&c0, &theta_xq, &yq_zh);
    fp2_mul_fp(&c1, &theta, xp);
    fp2_neg(&c1, &c1);
    fp2_mul_fp(&c2, &zh, yp);
    line_to_fp12(line, &c0, &c1, &c2);
    /* mixed add T + Q with doubled r (device convention) */
    fp2_t hm, rm, hh, r2, ii, j, v, zhm, x3, y3, z3, tmp;
    fp2_sub(&hm, &u2, &t->x);
    fp2_sub(&rm, &s2, &t->y);
    fp2_dbl(&rm, &rm);
    fp2_sqr(&hh, &hm);
    fp2_sqr(&r2, &rm);
    fp2_dbl(&ii, &hh);
    fp2_dbl(&ii, &ii);
    fp2_mul(&j, &hm, &ii);
    fp2_mul(&v, &t->x, &ii);
    fp2_mul(&zhm, &t->z, &hm);
    fp2_dbl(&tmp, &v);
    fp2_add(&tmp, &tmp, &j);
    fp2_sub(&x3, &r2, &tmp);
    fp2_sub(&tmp, &v, &x3);
    fp2_mul(&y3, &rm, &tmp);
    fp2_mul(&tmp, &t->y, &j);
    fp2_dbl(&tmp, &tmp);
    fp2_sub(&y3, &y3, &tmp);
    fp2_dbl(&z3, &zhm);
    t->x = x3;
    t->y = y3;
    t->z = z3;
}

/* f *= miller(P, Q) for affine P (G1) and Q (G2); result correct up to
 * subfield factors (shared final exp handles them). */
static void miller_loop_acc(fp12_t *f, const fp_t *xp, const fp_t *yp,
                            const fp2_t *xq, const fp2_t *yq) {
    g2_t t;
    t.x = *xq;
    t.y = *yq;
    fp2_one(&t.z);
    fp12_t acc, line;
    fp12_one(&acc);
    for (int bit = 62; bit >= 0; bit--) {
        fp12_sqr(&acc, &acc);
        miller_dbl_step(&t, &line, xp, yp);
        fp12_mul(&acc, &acc, &line);
        if ((FB_X_ABS >> bit) & 1) {
            miller_add_step(&t, &line, xq, yq, xp, yp);
            fp12_mul(&acc, &acc, &line);
        }
    }
    fp12_conj(&acc, &acc); /* negative parameter */
    fp12_mul(f, f, &acc);
}

/* ------------------------------------------------------------ exports -- */

#define FB_OK 1
#define FB_FAIL 0
#define FB_MALFORMED (-1)

/* batch verify with random linear combination:
 *   e(-g1, sum c_i s_i) * prod e(c_i agg_pk_i, H(m_i)) == 1
 * pubkeys: concatenated 48-byte compressed; pk_counts[i] pubkeys belong to
 * set i (aggregated in jacobian coords, the reference's main-thread
 * aggregation, chain/bls/utils.ts:5).  msgs: n * 32.  sigs: n * 96.
 * coeffs: odd 64-bit.  Infinity pubkeys/sigs are rejected. */
int fb_batch_verify(size_t n_sets, const uint8_t *pubkeys, const uint32_t *pk_counts,
                    const uint8_t *msgs, const uint8_t *sigs, const uint64_t *coeffs) {
    if (n_sets == 0) return FB_FAIL;
    fp12_t f;
    fp12_one(&f);
    g2_t sig_acc;
    g2_infinity(&sig_acc);
    size_t pk_off = 0;
    for (size_t i = 0; i < n_sets; i++) {
        /* aggregate this set's pubkeys */
        g1_t agg;
        g1_infinity(&agg);
        uint32_t cnt = pk_counts[i];
        if (cnt == 0) return FB_MALFORMED;
        for (uint32_t k = 0; k < cnt; k++) {
            g1_t pk;
            if (!g1_from_compressed(&pk, pubkeys + 48 * (pk_off + k)))
                return FB_MALFORMED;
            if (g1_is_infinity(&pk)) return FB_MALFORMED;
            if (!g1_subgroup_check(&pk)) return FB_MALFORMED;
            g1_add(&agg, &agg, &pk);
        }
        pk_off += cnt;
        if (g1_is_infinity(&agg)) return FB_MALFORMED;
        g2_t sig;
        if (!g2_from_compressed(&sig, sigs + 96 * i)) return FB_MALFORMED;
        if (g2_is_infinity(&sig)) return FB_MALFORMED;
        if (!g2_subgroup_check(&sig)) return FB_FAIL;
        uint64_t e[4] = {coeffs[i], 0, 0, 0};
        g2_t sig_c;
        g2_mul(&sig_c, &sig, e);
        g2_add(&sig_acc, &sig_acc, &sig_c);
        g1_t pk_c;
        g1_mul(&pk_c, &agg, e);
        fp_t ax, ay;
        if (!g1_to_affine(&ax, &ay, &pk_c)) return FB_MALFORMED;
        g2_t h;
        hash_to_g2(&h, msgs + 32 * i, 32);
        fp2_t hx, hy;
        if (!g2_to_affine(&hx, &hy, &h)) return FB_MALFORMED;
        miller_loop_acc(&f, &ax, &ay, &hx, &hy);
    }
    /* (-g1, sum c_i s_i) */
    if (!g2_is_infinity(&sig_acc)) {
        fp_t gx, gy;
        memcpy(gx.d, FB_G1_X, sizeof gx.d);
        memcpy(gy.d, FB_G1_Y, sizeof gy.d);
        fp_neg(&gy, &gy);
        fp2_t sx, sy;
        g2_to_affine(&sx, &sy, &sig_acc);
        miller_loop_acc(&f, &gx, &gy, &sx, &sy);
    }
    fp12_t out;
    fp12_final_exp(&out, &f);
    return fp12_is_one(&out) ? FB_OK : FB_FAIL;
}

/* single full verify: e(pk, H(m)) == e(g1, sig) */
int fb_verify_one(const uint8_t *pk48, const uint8_t *msg32, const uint8_t *sig96) {
    uint32_t one = 1;
    uint64_t c = 1;
    return fb_batch_verify(1, pk48, &one, msg32, sig96, &c);
}

/* final exponentiation + is_one on a raw Fq12 given as 12 x 48-byte
 * big-endian fp values in tower order [A.c0.c0, A.c0.c1, A.c1.c0, A.c1.c1,
 * A.c2.c0, A.c2.c1, B.c0.c0, ...] (A + B w, each fq6 = c0 + c1 v + c2 v^2,
 * each fq2 = c0 + c1 u).  This is the host-side tail of the split TPU
 * dispatch: the device returns its batched Miller product, the host
 * finishes.  Returns 1/0, -1 on out-of-range bytes. */
int fb_final_exp_is_one(const uint8_t *f_bytes) {
    fp12_t f;
    fp_t *slots[12] = {
        &f.c0.c0.c0, &f.c0.c0.c1, &f.c0.c1.c0, &f.c0.c1.c1,
        &f.c0.c2.c0, &f.c0.c2.c1, &f.c1.c0.c0, &f.c1.c0.c1,
        &f.c1.c1.c0, &f.c1.c1.c1, &f.c1.c2.c0, &f.c1.c2.c1};
    for (int i = 0; i < 12; i++)
        if (!fp_from_bytes(slots[i], f_bytes + 48 * i)) return FB_MALFORMED;
    fp12_t out;
    fp12_final_exp(&out, &f);
    return fp12_is_one(&out) ? FB_OK : FB_FAIL;
}

/* final exponentiation, bytes in/out (same layout) — differential tests */
int fb_final_exp(uint8_t *out_bytes, const uint8_t *f_bytes) {
    fp12_t f;
    fp_t *slots[12] = {
        &f.c0.c0.c0, &f.c0.c0.c1, &f.c0.c1.c0, &f.c0.c1.c1,
        &f.c0.c2.c0, &f.c0.c2.c1, &f.c1.c0.c0, &f.c1.c0.c1,
        &f.c1.c1.c0, &f.c1.c1.c1, &f.c1.c2.c0, &f.c1.c2.c1};
    for (int i = 0; i < 12; i++)
        if (!fp_from_bytes(slots[i], f_bytes + 48 * i)) return FB_MALFORMED;
    fp12_t out;
    fp12_final_exp(&out, &f);
    const fp_t *oslots[12] = {
        &out.c0.c0.c0, &out.c0.c0.c1, &out.c0.c1.c0, &out.c0.c1.c1,
        &out.c0.c2.c0, &out.c0.c2.c1, &out.c1.c0.c0, &out.c1.c0.c1,
        &out.c1.c1.c0, &out.c1.c1.c1, &out.c1.c2.c0, &out.c1.c2.c1};
    for (int i = 0; i < 12; i++) fp_to_bytes(out_bytes + 48 * i, oslots[i]);
    return FB_OK;
}

/* pairing e(P, Q)^3 on compressed inputs, bytes out — differential tests */
int fb_pairing(uint8_t *out_bytes, const uint8_t *pk48, const uint8_t *sig96) {
    g1_t p;
    g2_t q;
    if (!g1_from_compressed(&p, pk48)) return FB_MALFORMED;
    if (!g2_from_compressed(&q, sig96)) return FB_MALFORMED;
    if (g1_is_infinity(&p) || g2_is_infinity(&q)) return FB_MALFORMED;
    fp_t ax, ay;
    g1_to_affine(&ax, &ay, &p);
    fp2_t qx, qy;
    g2_to_affine(&qx, &qy, &q);
    fp12_t f;
    fp12_one(&f);
    miller_loop_acc(&f, &ax, &ay, &qx, &qy);
    fp12_t out;
    fp12_final_exp(&out, &f);
    const fp_t *oslots[12] = {
        &out.c0.c0.c0, &out.c0.c0.c1, &out.c0.c1.c0, &out.c0.c1.c1,
        &out.c0.c2.c0, &out.c0.c2.c1, &out.c1.c0.c0, &out.c1.c0.c1,
        &out.c1.c1.c0, &out.c1.c1.c1, &out.c1.c2.c0, &out.c1.c2.c1};
    for (int i = 0; i < 12; i++) fp_to_bytes(out_bytes + 48 * i, oslots[i]);
    return FB_OK;
}

/* hash_to_g2 -> affine coords out as 4 x 48 bytes (x.c0, x.c1, y.c0, y.c1) */
int fb_hash_to_g2(uint8_t *out_192, const uint8_t *msg, size_t msg_len) {
    g2_t h;
    hash_to_g2(&h, msg, msg_len);
    fp2_t x, y;
    if (!g2_to_affine(&x, &y, &h)) return FB_MALFORMED;
    fp_to_bytes(out_192, &x.c0);
    fp_to_bytes(out_192 + 48, &x.c1);
    fp_to_bytes(out_192 + 96, &y.c0);
    fp_to_bytes(out_192 + 144, &y.c1);
    return FB_OK;
}

/* aggregate compressed pubkeys; writes affine x||y (96 bytes, non-mont BE).
 * Returns FB_FAIL for an infinity aggregate. */
int fb_aggregate_pubkeys(size_t n, const uint8_t *pks, uint8_t *out96) {
    g1_t acc;
    g1_infinity(&acc);
    for (size_t i = 0; i < n; i++) {
        g1_t p;
        if (!g1_from_compressed(&p, pks + 48 * i)) return FB_MALFORMED;
        g1_add(&acc, &acc, &p);
    }
    fp_t x, y;
    if (!g1_to_affine(&x, &y, &acc)) return FB_FAIL;
    fp_to_bytes(out96, &x);
    fp_to_bytes(out96 + 48, &y);
    return FB_OK;
}

/* ------------------------------------------------------------- signing -- */

/* ZCash compressed encodings (inverse of g1_from_compressed /
 * g2_from_compressed above): 0x80 = compressed, 0x20 = y lexicographically
 * greater, 0xC0 = infinity. */
static void g1_to_compressed(uint8_t *out48, const g1_t *p) {
    fp_t x, y;
    if (!g1_to_affine(&x, &y, p)) {
        memset(out48, 0, 48);
        out48[0] = 0xC0;
        return;
    }
    fp_to_bytes(out48, &x);
    out48[0] |= 0x80;
    if (fp_is_lex_greater(&y)) out48[0] |= 0x20;
}

static void g2_to_compressed(uint8_t *out96, const g2_t *p) {
    fp2_t x, y;
    if (!g2_to_affine(&x, &y, p)) {
        memset(out96, 0, 96);
        out96[0] = 0xC0;
        return;
    }
    fp_to_bytes(out96, &x.c1); /* c1 first on the wire */
    fp_to_bytes(out96 + 48, &x.c0);
    out96[0] |= 0x80;
    if (fp2_is_lex_greater(&y)) out96[0] |= 0x20;
}

/* big-endian 32-byte scalar -> little-endian u64 limbs; returns 0 when the
 * scalar is 0 or >= r (invalid secret key). */
static int scalar_from_be32(uint64_t e[4], const uint8_t *sk32) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | sk32[(3 - i) * 8 + j];
        e[i] = v;
    }
    if (!(e[0] | e[1] | e[2] | e[3])) return 0;
    for (int i = 3; i >= 0; i--) {
        if (e[i] < FB_ORDER[i]) return 1;
        if (e[i] > FB_ORDER[i]) return 0;
    }
    return 0; /* == r */
}

/* BLS sign, VARIABLE TIME: sig = sk * hash_to_g2(msg), compressed out.
 * The scalar mult is the sliding double-and-add g2_mul — its branch
 * pattern and iteration count encode the secret key, so this path is for
 * DEV/INTEROP USE ONLY (dev-chain fixtures, test suites, interop vectors
 * — where the keys are the published interop secrets and speed is what
 * matters; it skips the pure-Python G2 ladder, ~3 orders of magnitude
 * slower).  Production validator signing goes through fb_sign_ct below;
 * validator/store.py enforces the default. */
int fb_sign(uint8_t *out_sig96, const uint8_t *sk32, const uint8_t *msg,
            size_t msg_len) {
    uint64_t e[4];
    if (!scalar_from_be32(e, sk32)) return FB_MALFORMED;
    g2_t h, s;
    hash_to_g2(&h, msg, msg_len);
    g2_mul(&s, &h, e);
    g2_to_compressed(out_sig96, &s);
    return FB_OK;
}

/* BLS sign, constant-time-safe: identical bytes to fb_sign, but the
 * scalar mult is the fixed-length double-and-always-add ladder
 * (g2_mul_ct) — uniform operation sequence regardless of the key.  ~2x
 * the cost of fb_sign (every bit pays the add), still ~500x the Python
 * oracle.  The default signing path for ValidatorStore. */
int fb_sign_ct(uint8_t *out_sig96, const uint8_t *sk32, const uint8_t *msg,
               size_t msg_len) {
    uint64_t e[4];
    if (!scalar_from_be32(e, sk32)) return FB_MALFORMED;
    g2_t h, s;
    hash_to_g2(&h, msg, msg_len);
    g2_mul_ct(&s, &h, e);
    g2_to_compressed(out_sig96, &s);
    return FB_OK;
}

/* aggregate-sign: one signature by the SUM of n secret keys over one
 * message — equal to aggregating n individual signatures over that message
 * ((sum sk_i) * H(m) = sum sk_i * H(m)), but pays ONE hash_to_g2 and ONE
 * scalar mult instead of n of each.  The whole-committee signing shape of
 * dev chains / sim fixtures (sync aggregates, committee attestations). */
int fb_sign_aggregate(uint8_t *out_sig96, const uint8_t *sks, size_t n,
                      const uint8_t *msg, size_t msg_len) {
    if (n == 0) return FB_MALFORMED;
    uint64_t acc[4] = {0, 0, 0, 0};
    for (size_t i = 0; i < n; i++) {
        uint64_t e[4];
        if (!scalar_from_be32(e, sks + 32 * i)) return FB_MALFORMED;
        /* acc = (acc + e) mod r: both < r so the sum < 2r; one conditional
         * subtract restores the range */
        unsigned __int128 carry = 0;
        for (int k = 0; k < 4; k++) {
            carry += (unsigned __int128)acc[k] + e[k];
            acc[k] = (uint64_t)carry;
            carry >>= 64;
        }
        int ge = (int)carry;
        if (!ge) {
            ge = 1;
            for (int k = 3; k >= 0; k--) {
                if (acc[k] < FB_ORDER[k]) { ge = 0; break; }
                if (acc[k] > FB_ORDER[k]) break;
            }
        }
        if (ge) {
            unsigned __int128 borrow = 0;
            for (int k = 0; k < 4; k++) {
                unsigned __int128 d =
                    (unsigned __int128)acc[k] - FB_ORDER[k] - (uint64_t)borrow;
                acc[k] = (uint64_t)d;
                borrow = (d >> 64) & 1;
            }
        }
    }
    if (!(acc[0] | acc[1] | acc[2] | acc[3])) return FB_FAIL; /* sum == 0 mod r */
    g2_t h, s;
    hash_to_g2(&h, msg, msg_len);
    g2_mul(&s, &h, acc);
    g2_to_compressed(out_sig96, &s);
    return FB_OK;
}

/* pk = sk * g1, compressed out. */
int fb_sk_to_pk(uint8_t *out_pk48, const uint8_t *sk32) {
    uint64_t e[4];
    if (!scalar_from_be32(e, sk32)) return FB_MALFORMED;
    g1_t g, p;
    memcpy(g.x.d, FB_G1_X, sizeof g.x.d);
    memcpy(g.y.d, FB_G1_Y, sizeof g.y.d);
    memcpy(g.z.d, FB_R1, sizeof g.z.d);
    g1_mul(&p, &g, e);
    g1_to_compressed(out_pk48, &p);
    return FB_OK;
}

/* aggregate compressed signatures -> compressed 96-byte aggregate. */
int fb_aggregate_sigs(size_t n, const uint8_t *sigs, uint8_t *out96) {
    g2_t acc;
    g2_infinity(&acc);
    for (size_t i = 0; i < n; i++) {
        g2_t p;
        if (!g2_from_compressed(&p, sigs + 96 * i)) return FB_MALFORMED;
        g2_add(&acc, &acc, &p);
    }
    g2_to_compressed(out96, &acc);
    return FB_OK;
}

/* aggregate compressed pubkeys -> compressed 48-byte aggregate. */
int fb_aggregate_pubkeys_c(size_t n, const uint8_t *pks, uint8_t *out48) {
    g1_t acc;
    g1_infinity(&acc);
    for (size_t i = 0; i < n; i++) {
        g1_t p;
        if (!g1_from_compressed(&p, pks + 48 * i)) return FB_MALFORMED;
        g1_add(&acc, &acc, &p);
    }
    g1_to_compressed(out48, &acc);
    return FB_OK;
}

/* self-test: e(g1, g2) is non-one, bilinearity e([2]g1, g2) == e(g1, [2]g2),
 * and sha256("") matches the known digest. */
int fb_selftest(void) {
    /* sha256 KAT */
    uint8_t d[32];
    sha256_ctx c;
    sha256_init(&c);
    sha256_final(&c, d);
    static const uint8_t empty[32] = {
        0xe3, 0xb0, 0xc4, 0x42, 0x98, 0xfc, 0x1c, 0x14, 0x9a, 0xfb, 0xf4,
        0xc8, 0x99, 0x6f, 0xb9, 0x24, 0x27, 0xae, 0x41, 0xe4, 0x64, 0x9b,
        0x93, 0x4c, 0xa4, 0x95, 0x99, 0x1b, 0x78, 0x52, 0xb8, 0x55};
    if (memcmp(d, empty, 32) != 0) return 0;
    /* pairing bilinearity */
    g1_t g1, g1_2;
    g2_t g2, g2_2;
    memcpy(g1.x.d, FB_G1_X, sizeof g1.x.d);
    memcpy(g1.y.d, FB_G1_Y, sizeof g1.y.d);
    memcpy(g1.z.d, FB_R1, sizeof g1.z.d);
    memcpy(g2.x.c0.d, FB_G2_X[0], 48);
    memcpy(g2.x.c1.d, FB_G2_X[1], 48);
    memcpy(g2.y.c0.d, FB_G2_Y[0], 48);
    memcpy(g2.y.c1.d, FB_G2_Y[1], 48);
    fp2_one(&g2.z);
    g1_double(&g1_2, &g1);
    g2_double(&g2_2, &g2);
    fp_t ax, ay, bx, by;
    fp2_t qx, qy, rx, ry;
    g1_to_affine(&ax, &ay, &g1);
    g1_to_affine(&bx, &by, &g1_2);
    g2_to_affine(&qx, &qy, &g2);
    g2_to_affine(&rx, &ry, &g2_2);
    fp12_t fa, fb, ea, eb;
    fp12_one(&fa);
    miller_loop_acc(&fa, &bx, &by, &qx, &qy); /* e([2]g1, g2) */
    fp12_final_exp(&ea, &fa);
    fp12_one(&fb);
    miller_loop_acc(&fb, &ax, &ay, &rx, &ry); /* e(g1, [2]g2) */
    fp12_final_exp(&eb, &fb);
    if (fp12_is_one(&ea)) return 0;
    /* compare */
    if (memcmp(&ea, &eb, sizeof ea) != 0) {
        /* allow representation differences: compare via subtraction */
        fp12_t inv, quot;
        fp12_inv(&inv, &eb);
        fp12_mul(&quot, &ea, &inv);
        if (!fp12_is_one(&quot)) return 0;
    }
    /* subgroup checks accept the generators */
    if (!g1_subgroup_check(&g1)) return 0;
    if (!g2_subgroup_check(&g2)) return 0;
    /* constant-time ladder == variable-time ladder (same compressed
     * bytes for the same scalar), including a low-Hamming-weight scalar
     * whose fixed-length handling is the part g2_mul skips */
    {
        uint8_t sk[32] = {0}, a[96], b[96];
        sk[31] = 5;
        if (fb_sign(a, sk, (const uint8_t *)"ct", 2) != FB_OK) return 0;
        if (fb_sign_ct(b, sk, (const uint8_t *)"ct", 2) != FB_OK) return 0;
        if (memcmp(a, b, 96) != 0) return 0;
        sk[0] = 0x42;
        if (fb_sign(a, sk, (const uint8_t *)"ct2", 3) != FB_OK) return 0;
        if (fb_sign_ct(b, sk, (const uint8_t *)"ct2", 3) != FB_OK) return 0;
        if (memcmp(a, b, 96) != 0) return 0;
    }
    return 1;
}
