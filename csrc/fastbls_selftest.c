/* Standalone driver for sanitizer runs (SURVEY section 5.2: the native
 * host code must have an ASAN/UBSAN story — the reference has none to
 * copy, its native code lives in deps).
 *
 * Build + run (tools/sanitize_native.sh):
 *   cc -fsanitize=address,undefined -g fastbls_selftest.c -o t && ./t
 * Exercises: pairing selftest, hash-to-G2, compressed-point parsing on
 * hostile inputs, batch verify with a malformed set.
 */

#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include "fastbls.c"

int main(void) {
    if (!fb_selftest()) {
        fprintf(stderr, "selftest FAILED\n");
        return 1;
    }
    /* hash_to_g2 over varied message lengths (exercises expand_message) */
    uint8_t out[192];
    uint8_t msg[257];
    for (int n = 0; n <= 256; n += 64) {
        memset(msg, (uint8_t)n, (size_t)n);
        if (fb_hash_to_g2(out, msg, (size_t)n) != FB_OK) {
            fprintf(stderr, "hash_to_g2 FAILED at len %d\n", n);
            return 1;
        }
    }
    /* hostile compressed points: every flag pattern over garbage bytes */
    uint8_t pt[96];
    g1_t g1p_;
    g2_t g2p_;
    for (int flags = 0; flags < 256; flags++) {
        memset(pt, 0xA5, sizeof pt);
        pt[0] = (uint8_t)flags;
        (void)g1_from_compressed(&g1p_, pt);
        (void)g2_from_compressed(&g2p_, pt);
    }
    /* batch verify with malformed inputs must return FB_MALFORMED, not
     * read out of bounds */
    uint8_t pk[48], sig[96], m[32];
    memset(pk, 0xFF, sizeof pk);
    memset(sig, 0xFF, sizeof sig);
    memset(m, 0, sizeof m);
    uint32_t one = 1;
    uint64_t coeff = 3;
    if (fb_batch_verify(1, pk, &one, m, sig, &coeff) != FB_MALFORMED) {
        fprintf(stderr, "malformed input not rejected\n");
        return 1;
    }
    /* final-exp bytes out of range must be rejected */
    uint8_t f_bytes[576];
    memset(f_bytes, 0xFF, sizeof f_bytes);
    if (fb_final_exp_is_one(f_bytes) != FB_MALFORMED) {
        fprintf(stderr, "out-of-range fq12 not rejected\n");
        return 1;
    }
    printf("sanitizer selftest OK\n");
    return 0;
}
