/* hashtree.c — batched SHA-256 merkle-layer hashing for the SSZ host path.
 *
 * The runtime-native analog of the reference's as-sha256/hashtree deps
 * (SURVEY.md §2.9: ssz merkleization is a native concern there too): one
 * C call hashes a whole tree layer (consecutive 64-byte blocks -> 32-byte
 * digests), removing the per-pair Python/hashlib round trips that
 * dominate hash_tree_root on beacon states.
 *
 * SHA-256 per FIPS 180-4.  Each 64-byte input block is one single-block
 * message (length 512 bits), so the padding block is constant and the
 * schedule for it is precomputable — we fold it in directly.
 *
 * Build: cc -O3 -shared -fPIC -o libhashtree.so hashtree.c
 * Binding: lodestar_tpu/native/hashtree.py (ctypes).
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))
#define CH(x, y, z) (((x) & (y)) ^ (~(x) & (z)))
#define MAJ(x, y, z) (((x) & (y)) ^ ((x) & (z)) ^ ((y) & (z)))
#define EP0(x) (ROTR(x, 2) ^ ROTR(x, 13) ^ ROTR(x, 22))
#define EP1(x) (ROTR(x, 6) ^ ROTR(x, 11) ^ ROTR(x, 25))
#define SIG0(x) (ROTR(x, 7) ^ ROTR(x, 18) ^ ((x) >> 3))
#define SIG1(x) (ROTR(x, 17) ^ ROTR(x, 19) ^ ((x) >> 10))

static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                               0xa54ff53a, 0x510e527f, 0x9b05688c,
                               0x1f83d9ab, 0x5be0cd19};

static void compress(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  uint32_t a, b, c, d, e, f, g, h, t1, t2;
  int i;
  for (i = 0; i < 16; i++)
    w[i] = ((uint32_t)block[i * 4] << 24) | ((uint32_t)block[i * 4 + 1] << 16) |
           ((uint32_t)block[i * 4 + 2] << 8) | (uint32_t)block[i * 4 + 3];
  for (i = 16; i < 64; i++)
    w[i] = SIG1(w[i - 2]) + w[i - 7] + SIG0(w[i - 15]) + w[i - 16];
  a = state[0]; b = state[1]; c = state[2]; d = state[3];
  e = state[4]; f = state[5]; g = state[6]; h = state[7];
  for (i = 0; i < 64; i++) {
    t1 = h + EP1(e) + CH(e, f, g) + K[i] + w[i];
    t2 = EP0(a) + MAJ(a, b, c);
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

/* The constant second block of every 64-byte message: 0x80 pad + length
 * 512 bits.  Precompute its expanded schedule contribution by just
 * compressing it normally (cheap enough; the win is batching). */
static const uint8_t PADBLOCK[64] = {[0] = 0x80, [62] = 0x02, [63] = 0x00};

#if defined(__x86_64__)
#include <immintrin.h>

/* SHA-NI single-block compress (Intel SHA extensions round pattern). */
__attribute__((target("sha,sse4.1")))
static void compress_ni(uint32_t state[8], const uint8_t block[64]) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i STATE0, STATE1, MSG, TMP, MSG0, MSG1, MSG2, MSG3;
  __m128i ABEF_SAVE, CDGH_SAVE;

  TMP = _mm_loadu_si128((const __m128i *)&state[0]);    /* DCBA */
  STATE1 = _mm_loadu_si128((const __m128i *)&state[4]); /* HGFE */
  TMP = _mm_shuffle_epi32(TMP, 0xB1);       /* CDAB */
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B); /* EFGH */
  STATE0 = _mm_alignr_epi8(TMP, STATE1, 8); /* ABEF */
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0); /* CDGH */

  ABEF_SAVE = STATE0;
  CDGH_SAVE = STATE1;

#define QROUND(Ki, M)                                                       \
  do {                                                                      \
    MSG = _mm_add_epi32(M, _mm_loadu_si128((const __m128i *)&K[Ki]));       \
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);                    \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                                     \
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);                    \
  } while (0)

  MSG0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(block + 0)), MASK);
  MSG1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(block + 16)), MASK);
  MSG2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(block + 32)), MASK);
  MSG3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(block + 48)), MASK);

  QROUND(0, MSG0);
  QROUND(4, MSG1);
  QROUND(8, MSG2);
  QROUND(12, MSG3);

#define EXPAND(Ma, Mb, Mc, Md)                                              \
  do {                                                                      \
    Ma = _mm_sha256msg2_epu32(                                              \
        _mm_add_epi32(_mm_sha256msg1_epu32(Ma, Mb),                         \
                      _mm_alignr_epi8(Md, Mc, 4)),                          \
        Md);                                                                \
  } while (0)

  { int r;
    for (r = 16; r < 64; r += 16) {
      EXPAND(MSG0, MSG1, MSG2, MSG3);
      QROUND(r + 0, MSG0);
      EXPAND(MSG1, MSG2, MSG3, MSG0);
      QROUND(r + 4, MSG1);
      EXPAND(MSG2, MSG3, MSG0, MSG1);
      QROUND(r + 8, MSG2);
      EXPAND(MSG3, MSG0, MSG1, MSG2);
      QROUND(r + 12, MSG3);
    }
  }
#undef QROUND
#undef EXPAND

  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);

  TMP = _mm_shuffle_epi32(STATE0, 0x1B);       /* FEBA */
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);    /* DCHG */
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0); /* DCBA */
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);    /* HGFE */

  _mm_storeu_si128((__m128i *)&state[0], STATE0);
  _mm_storeu_si128((__m128i *)&state[4], STATE1);
}

static int have_shani(void) {
  return __builtin_cpu_supports("sha") && __builtin_cpu_supports("sse4.1");
}
#else
static void compress_ni(uint32_t state[8], const uint8_t block[64]) {
  compress(state, block);
}
static int have_shani(void) { return 0; }
#endif

/* hash `n` consecutive 64-byte blocks from `in` into `n` 32-byte digests */
void hashtree_hash_layer(const uint8_t *in, size_t n, uint8_t *out) {
  size_t i;
  int j;
  int ni = have_shani();
  for (i = 0; i < n; i++) {
    uint32_t s[8];
    memcpy(s, H0, sizeof(s));
    if (ni) {
      compress_ni(s, in + i * 64);
      compress_ni(s, PADBLOCK);
    } else {
      compress(s, in + i * 64);
      compress(s, PADBLOCK);
    }
    for (j = 0; j < 8; j++) {
      out[i * 32 + j * 4] = (uint8_t)(s[j] >> 24);
      out[i * 32 + j * 4 + 1] = (uint8_t)(s[j] >> 16);
      out[i * 32 + j * 4 + 2] = (uint8_t)(s[j] >> 8);
      out[i * 32 + j * 4 + 3] = (uint8_t)(s[j]);
    }
  }
}

/* full sha256 for arbitrary input (digest of `len` bytes) — used by the
 * snappy codec and signing-root helpers when the lib is loaded anyway */
void hashtree_sha256(const uint8_t *in, size_t len, uint8_t *out32) {
  uint32_t s[8];
  uint8_t block[64];
  size_t full = len / 64, i;
  uint64_t bits = (uint64_t)len * 8;
  memcpy(s, H0, sizeof(s));
  for (i = 0; i < full; i++) compress(s, in + i * 64);
  {
    size_t rem = len - full * 64;
    memset(block, 0, 64);
    memcpy(block, in + full * 64, rem);
    block[rem] = 0x80;
    if (rem >= 56) {
      compress(s, block);
      memset(block, 0, 64);
    }
    for (i = 0; i < 8; i++) block[56 + i] = (uint8_t)(bits >> (56 - 8 * i));
    compress(s, block);
  }
  for (i = 0; i < 8; i++) {
    out32[i * 4] = (uint8_t)(s[i] >> 24);
    out32[i * 4 + 1] = (uint8_t)(s[i] >> 16);
    out32[i * 4 + 2] = (uint8_t)(s[i] >> 8);
    out32[i * 4 + 3] = (uint8_t)(s[i]);
  }
}
